"""tpu_dp.serve — batched-inference subsystem tests (docs/SERVING.md).

What must hold, in order of importance:

1. **Correctness under batching**: a request's predictions are identical
   to running the model directly on its images — coalescing, padding, and
   bucket choice can never leak into results.
2. **Zero retraces**: after one warmup call per bucket, a 200-request
   mixed-size load hits only pre-compiled programs (the RecompileGuard
   raises otherwise — the engine's default).
3. **Exact books**: the loadgen's caller-side ground truth (accepted /
   shed-by-reason / completed / deadline-missed, image counts) matches
   the `tpu_dp.obs` serve counters and the device-side donated stats
   EXACTLY — telemetry that can drift from truth is worse than none.
4. **Attributable faults**: a deterministic `TPU_DP_FAULT=delay:`
   straggler during serving surfaces in the obs heartbeats and in the
   affected requests' device spans, with the books still exact.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from tpu_dp.obs.counters import counters
from tpu_dp.serve import (
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    BucketLadder,
    DynamicBatcher,
    InferenceEngine,
    RequestQueue,
    ShedError,
    arrival_offsets,
    parse_buckets,
    run_load,
)

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def net_model():
    from tpu_dp.models import build_model

    model = build_model("net")
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    return model, variables["params"]


def make_engine(net_model, **kw):
    model, params = net_model
    kw.setdefault("buckets", (1, 2, 4, 8))
    kw.setdefault("slo_ms", 500.0)
    return InferenceEngine(model, params, **kw)


def direct_predictions(net_model, images_u8):
    """The unbatched reference forward for a request's images."""
    from tpu_dp.data.cifar import normalize

    model, params = net_model
    logits = model.apply(
        {"params": params}, normalize(np.asarray(images_u8)), train=False
    )
    return np.asarray(logits.argmax(axis=-1))


# -- ladder + batcher (pure logic) ----------------------------------------

def test_bucket_ladder_pick_and_validation():
    ladder = BucketLadder((1, 2, 4, 8))
    assert ladder.max_batch == 8
    assert [ladder.pick(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        ladder.pick(9)
    with pytest.raises(ValueError):
        ladder.pick(0)
    with pytest.raises(ValueError):
        BucketLadder(())
    with pytest.raises(ValueError):
        BucketLadder((4, 2))  # not ascending
    with pytest.raises(ValueError):
        BucketLadder((2, 2, 4))  # duplicate
    with pytest.raises(ValueError):
        BucketLadder((0, 2))


def test_parse_buckets():
    assert parse_buckets("1,2,4") == (1, 2, 4)
    with pytest.raises(ValueError):
        parse_buckets("")
    with pytest.raises(ValueError):
        parse_buckets("1,x")


def _mk_queue(**kw):
    kw.setdefault("max_depth", 8)
    kw.setdefault("default_slo_ms", 1000.0)
    return RequestQueue(**kw)


def test_queue_sheds_on_depth_with_reason_and_counters():
    q = _mk_queue(max_depth=2)
    before = counters.get("serve.shed.queue_full")
    q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    with pytest.raises(ShedError) as ei:
        q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    assert ei.value.reason == SHED_QUEUE_FULL
    assert counters.get("serve.shed.queue_full") == before + 1


def test_queue_rejects_malformed_requests():
    q = _mk_queue(max_request=4)
    with pytest.raises(ValueError):
        q.submit(np.zeros((1, 16, 16, 3), np.uint8))  # wrong shape
    with pytest.raises(ValueError):
        q.submit(np.zeros((1, 32, 32, 3), np.float32))  # wrong dtype
    with pytest.raises(ValueError):
        q.submit(np.zeros((5, 32, 32, 3), np.uint8))  # above max bucket
    assert len(q) == 0  # nothing was admitted


def test_queue_submit_after_close_sheds_synchronously():
    """ISSUE 11 satellite: `submit` after `close()` must shed `closed` AT
    ADMISSION — immediate typed answer, handle resolved, counters exact —
    never rely on a dispatch loop (possibly already dead) to notice."""
    q = _mk_queue()
    q.close()
    before = counters.get("serve.shed.closed")
    before_c0 = counters.get("serve.shed.c0")
    with pytest.raises(ShedError) as ei:
        q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    assert ei.value.reason == "closed"
    assert counters.get("serve.shed.closed") == before + 1
    assert counters.get("serve.shed.c0") == before_c0 + 1
    assert len(q) == 0


def test_queue_full_evicts_lowest_class_first():
    """Burst overload sheds the bronze tier before gold: an incoming
    higher-class request evicts the youngest queued request of the worst
    class (typed queue_full shed) instead of being rejected itself."""
    q = _mk_queue(max_depth=2)
    h_gold = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=0)
    h_bronze_old = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=2)
    # Full. A same-or-worse class submit sheds itself...
    with pytest.raises(ShedError) as ei:
        q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=2)
    assert ei.value.reason == SHED_QUEUE_FULL
    # ...but a better-class submit evicts the queued bronze request.
    h_silver = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=1)
    assert h_bronze_old.done() and h_bronze_old.shed_reason == SHED_QUEUE_FULL
    assert not h_gold.done() and not h_silver.done()
    # Dispatch order is (class, arrival): gold before silver.
    batch, _ = q.collect(max_images=8)
    assert [r.handle for r in batch] == [h_gold, h_silver]


def test_doomed_request_never_evicts_viable_victim():
    """A request already below the shed headroom sheds `deadline` BEFORE
    the full-queue eviction decision — it must not cost a serveable
    lower-class request its slot."""
    q = _mk_queue(max_depth=1, shed_headroom_ms=10.0)
    h_bronze = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=2)
    with pytest.raises(ShedError) as ei:
        q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=0,
                 slo_ms=5.0)
    assert ei.value.reason == SHED_DEADLINE
    assert not h_bronze.done() and len(q) == 1


def test_queue_class_order_is_fifo_within_class():
    q = _mk_queue(max_depth=16)
    h_b1 = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=1)
    h_a1 = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=0)
    h_b2 = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=1)
    h_a2 = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_class=0)
    batch, _ = q.collect(max_images=8)
    assert [r.handle for r in batch] == [h_a1, h_a2, h_b1, h_b2]


def test_requeue_preserves_admission_books():
    """Failover re-admission re-counts nothing: accepted once at submit,
    back at the queue head with arrival/deadline intact."""
    q = _mk_queue(max_depth=4)
    accepted_before = counters.get("serve.accepted")
    q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    q.submit(np.zeros((2, 32, 32, 3), np.uint8))
    batch, _ = q.collect(max_images=8)
    assert len(batch) == 2 and len(q) == 0
    q.requeue(batch)
    assert len(q) == 2 and q.pending_images() == 3
    again, _ = q.collect(max_images=8)
    assert [r.req_id for r in again] == [r.req_id for r in batch]
    assert counters.get("serve.accepted") == accepted_before + 2


def test_handle_resolves_exactly_once():
    """The claim guard: a second resolution (the failover double-serve
    race) is discarded — first answer wins, books untouched."""
    from tpu_dp.serve import RequestHandle

    h = RequestHandle(0, 1)
    assert h._shed("replica_failed")
    assert not h._resolve(np.zeros(1), np.zeros(1), 1.0, False, {})
    assert h.shed_reason == "replica_failed" and h.predictions is None
    h2 = RequestHandle(1, 1)
    assert h2._resolve(np.zeros(1), np.zeros(1), 1.0, False, {})
    assert not h2._shed("closed")
    assert h2.ok and h2.shed_reason is None


def test_queue_sheds_at_admission_below_headroom():
    q = _mk_queue(shed_headroom_ms=10.0)
    with pytest.raises(ShedError) as ei:
        q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_ms=5.0)
    assert ei.value.reason == SHED_DEADLINE
    # A budget above the headroom is admitted.
    h = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_ms=50.0)
    assert not h.done()


def test_queue_collect_expires_coalesces_fifo_never_splits():
    q = _mk_queue(max_depth=16)
    h_exp = q.submit(np.zeros((1, 32, 32, 3), np.uint8), slo_ms=0.0)
    h1 = q.submit(np.ones((2, 32, 32, 3), np.uint8))
    h2 = q.submit(np.ones((3, 32, 32, 3), np.uint8))
    h3 = q.submit(np.ones((4, 32, 32, 3), np.uint8))  # 2+3+4 > 8: no split
    batch, expired = q.collect(max_images=8)
    assert [r.handle for r in expired] == [h_exp]
    assert h_exp.done() and h_exp.shed_reason == SHED_DEADLINE
    assert [r.handle for r in batch] == [h1, h2]  # FIFO prefix that fits
    assert len(q) == 1  # h3 stays whole for the next batch
    batch2, _ = q.collect(max_images=8)
    assert [r.handle for r in batch2] == [h3]


def test_batcher_pads_masks_and_slices():
    q = _mk_queue()
    b = DynamicBatcher(q, BucketLadder((1, 2, 4, 8)), max_wait_ms=1.0)
    q.submit(np.full((2, 32, 32, 3), 7, np.uint8))
    q.submit(np.full((1, 32, 32, 3), 9, np.uint8))
    reqs, expired = q.collect(8)
    formed = b.form(reqs, expired, time.perf_counter())
    assert formed.bucket == 4 and formed.valid == 3
    assert formed.images.shape == (4, 32, 32, 3)
    assert formed.weight.tolist() == [1.0, 1.0, 1.0, 0.0]
    assert (formed.images[formed.slices[0]] == 7).all()
    assert (formed.images[formed.slices[1]] == 9).all()
    assert (formed.images[3] == 0).all()  # padding rows are zero
    assert formed.occupancy == pytest.approx(0.75)


def test_await_work_fill_and_wait_triggers():
    q = _mk_queue(max_depth=32)
    # Fill trigger: pending images reach the target immediately.
    q.submit(np.zeros((4, 32, 32, 3), np.uint8))
    assert q.await_work(target_images=4, max_wait_s=60.0, timeout_s=1.0) \
        == "fill"
    q.collect(8)
    # Wait trigger: one small request, short max_wait.
    q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    t0 = time.perf_counter()
    assert q.await_work(target_images=8, max_wait_s=0.02, timeout_s=5.0) \
        == "wait"
    assert time.perf_counter() - t0 < 2.0
    q.collect(8)
    # Timeout trigger: empty queue.
    assert q.await_work(8, 0.02, timeout_s=0.01) == "timeout"
    # Timeout with PENDING work younger than max_wait: must NOT dispatch
    # — returning "wait" here would silently cap the configured max_wait
    # at the dispatch loop's poll interval.
    q.submit(np.zeros((1, 32, 32, 3), np.uint8))
    assert q.await_work(8, max_wait_s=10.0, timeout_s=0.01) == "timeout"
    q.collect(8)
    # Closed + drained.
    q.close()
    assert q.await_work(8, 0.02, timeout_s=1.0) == "closed"


# -- the engine ------------------------------------------------------------

def test_engine_predictions_match_direct_forward(net_model):
    rng = np.random.default_rng(3)
    engine = make_engine(net_model)
    with engine:
        payloads = [
            rng.integers(0, 256, size=(k, 32, 32, 3)).astype(np.uint8)
            for k in (1, 3, 2, 4, 1, 2)
        ]
        handles = [engine.submit(p) for p in payloads]
        for p, h in zip(payloads, handles):
            assert h.wait(30.0)
            assert h.ok
            np.testing.assert_array_equal(
                h.predictions, direct_predictions(net_model, p)
            )
            assert h.confidence.shape == (p.shape[0],)
            assert ((h.confidence > 0) & (h.confidence <= 1)).all()


def test_engine_200_request_mixed_load_zero_retraces_exact_books(net_model):
    """The acceptance-criteria run (ISSUE 6): 200 mixed-size requests on
    the 8-device CPU mesh — zero post-warmup retraces, per-request
    percentiles + SLO attainment from obs spans, and shed/deadline
    counters exactly consistent with the loadgen's ground truth."""
    assert jax.device_count() == 8
    retraces_before = counters.get("recompile.retraces")
    engine = make_engine(net_model, buckets=(1, 2, 4, 8, 16, 32),
                         slo_ms=500.0)
    warm = engine.warmup()
    assert set(warm) == {1, 2, 4, 8, 16, 32}
    engine.start(warmup=False)
    try:
        report = run_load(engine, n_requests=200, pattern="poisson",
                          rate_rps=600.0, sizes=(1, 2, 3, 4), seed=1)
    finally:
        engine.stop()
    truth = report["ground_truth"]
    assert truth["submitted"] == 200
    assert truth["completed"] == truth["accepted"] == 200
    assert truth["unresolved"] == 0
    assert report["consistent"], (truth, report["counters"])
    # Zero retraces: per-guard and in the global recompile counter.
    assert report["retraces"] == 0
    assert counters.get("recompile.retraces") == retraces_before
    # Percentiles + attainment come from the recorded spans.
    assert report["latency_ms"]["n"] == 200
    assert report["latency_ms"]["p50_ms"] <= report["latency_ms"]["p95_ms"] \
        <= report["latency_ms"]["p99_ms"]
    assert report["slo"]["attainment"] is not None
    for span in ("queue_wait", "batch_form", "h2d", "device", "d2h"):
        assert report["spans"][span]["n"] == 200, span
    # Device-side ground truth: the donated stats counted every real
    # image exactly once (padding never leaks in).
    assert report["device_stats"]["served"] == truth["images_served"]
    assert sum(report["device_stats"]["class_counts"]) \
        == truth["images_served"]
    # Mixed sizes actually exercised multiple buckets.
    assert len(report["bucket_counts"]) >= 2


def test_burst_overload_sheds_with_exact_books(net_model):
    """A burst into a tiny queue must shed (queue_full), and every shed
    must be visible to BOTH sides identically."""
    engine = make_engine(net_model, buckets=(1, 2, 4), max_queue=3,
                         max_wait_ms=20.0)
    with engine:
        report = run_load(engine, n_requests=60, pattern="burst",
                          burst=20, rate_rps=5000.0, sizes=(1, 2), seed=2)
    truth = report["ground_truth"]
    assert truth["shed"] > 0
    assert truth["shed_by_reason"].get(SHED_QUEUE_FULL, 0) > 0
    assert truth["completed"] + truth["shed"] == 60
    assert report["consistent"], (truth, report["counters"])


def test_zero_budget_requests_all_shed_or_missed(net_model):
    """slo_ms=0: every admitted request either sheds on expiry or
    completes past its deadline — nothing can be silently on-time."""
    engine = make_engine(net_model)
    with engine:
        report = run_load(engine, n_requests=20, pattern="poisson",
                          rate_rps=2000.0, sizes=(1,), slo_ms=0.0, seed=3)
    truth = report["ground_truth"]
    assert truth["completed"] + truth["shed"] == 20
    assert truth["shed"] + truth["deadline_missed"] == 20
    assert report["consistent"], (truth, report["counters"])


def test_fault_delay_surfaces_in_heartbeats_and_spans(net_model, tmp_path):
    """A TPU_DP_FAULT=delay: straggler during serving is attributable:
    the delayed batch's heartbeat shows the inflated step time, the
    affected requests' device span carries the delay, and the books stay
    exact (ISSUE 6 satellite)."""
    delay_ms = 250.0
    engine = make_engine(
        net_model,
        obs_dir=str(tmp_path),
        fault=f"delay:step=2,ms={delay_ms:.0f}",
    )
    with engine:
        handles = []
        for i in range(5):  # sequential singles → one batch per request
            h = engine.submit(
                np.full((1, 32, 32, 3), i, np.uint8)
            )
            assert h.wait(30.0) and h.ok
            handles.append(h)
    # Spans: exactly the delayed batch's requests carry the delay.
    slow = [h for h in handles if h.spans["device"] >= delay_ms * 0.9]
    assert len(slow) == 1, [round(h.spans["device"], 1) for h in handles]
    # Heartbeats: the straggling batch is visible from the files alone.
    beats = []
    for line in (tmp_path / "heartbeat_r00000.jsonl").read_text().splitlines():
        beats.append(json.loads(line))
    assert len(beats) == 5
    slow_beats = [b for b in beats if b["step_ms"] >= delay_ms * 0.9]
    assert len(slow_beats) == 1
    # batch_index is 0-based when the injector fires at step>=2 → the
    # third batch; its heartbeat step counter is 3 (1-based post-beat).
    assert slow_beats[0]["step"] == 3
    # Books stay exact around the fault.
    assert engine.device_stats()["served"] == 5
    assert engine.retraces == 0


def test_stop_without_drain_sheds_pending_quickly(net_model):
    """stop(drain=False) must abandon, not drain: a request parked behind
    a long batching window is shed with reason `closed` and the shutdown
    returns promptly instead of serving out the queue."""
    engine = make_engine(net_model, max_wait_ms=30_000.0)  # parks requests
    engine.start()
    h = engine.submit(np.zeros((1, 32, 32, 3), np.uint8))
    t0 = time.perf_counter()
    engine.stop(drain=False)
    assert time.perf_counter() - t0 < 5.0  # not the 30s batching window
    assert h.done() and h.shed_reason == "closed"


def test_engine_error_sheds_queued_requests(net_model):
    """A dispatch-thread failure must not leave callers blocked: queued
    requests shed with reason engine_error and stop() re-raises."""
    engine = make_engine(net_model, fault="kill:step=10000")  # inert
    engine.start()

    def boom(*a, **k):
        raise RuntimeError("injected dispatch failure")

    # Replace every bucket program with a failing one.
    for bucket in engine.ladder.buckets:
        engine._programs[bucket] = boom
    h = engine.submit(np.zeros((1, 32, 32, 3), np.uint8))
    assert h.wait(30.0)
    assert h.shed_reason == "engine_error"
    with pytest.raises(RuntimeError, match="dispatch thread failed"):
        engine.stop()


# -- checkpoint satellite ---------------------------------------------------

def test_load_params_only_roundtrip_ignores_opt_layout(tmp_path, mesh8):
    """Params-only load: exact round trip, no optimizer needed — including
    from a checkpoint whose opt state was written in the SHARDED layout
    (flat 1-D shards the inference side knows nothing about)."""
    from tpu_dp.checkpoint import (
        load_params_only, save_checkpoint,
    )
    from tpu_dp.models import build_model
    from tpu_dp.train import SGD, create_train_state, shard_optimizer

    model = build_model("net")
    opt = shard_optimizer(SGD(momentum=0.9), 8)
    state = create_train_state(
        model, jax.random.PRNGKey(7),
        np.zeros((1, 32, 32, 3), np.float32), opt,
    )
    save_checkpoint(tmp_path, state, {"config": {"model": {"name": "net"}}})
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    params, batch_stats, meta = load_params_only(
        tmp_path, variables["params"]
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(state.params),
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert batch_stats == {}
    assert meta["config"]["model"]["name"] == "net"


def test_load_params_only_rejects_bare_params_export(tmp_path):
    from tpu_dp.checkpoint import load_params_only, save_params
    from tpu_dp.models import build_model

    model = build_model("net")
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    save_params(tmp_path / "state.msgpack", variables["params"])
    with pytest.raises(ValueError, match="load_params"):
        load_params_only(tmp_path, variables["params"])


def test_engine_from_checkpoint_serves_trained_params(tmp_path, net_model):
    """End to end: a CheckpointManager-written training checkpoint serves
    via from_checkpoint (model rebuilt from meta, params-only), and its
    predictions equal the direct forward on the restored params."""
    from tpu_dp.checkpoint import CheckpointManager
    from tpu_dp.models import build_model
    from tpu_dp.train import SGD, create_train_state

    model = build_model("net")
    state = create_train_state(
        model, jax.random.PRNGKey(11),
        np.zeros((1, 32, 32, 3), np.float32), SGD(momentum=0.9),
    )
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        mgr.save(state, {"config": {"model": {"name": "net"},
                                    "data": {"dataset": "cifar10"}}},
                 step=5)
    engine = InferenceEngine.from_checkpoint(
        tmp_path, buckets=(1, 2, 4), slo_ms=500.0
    )
    rng = np.random.default_rng(5)
    images = rng.integers(0, 256, size=(3, 32, 32, 3)).astype(np.uint8)
    with engine:
        h = engine.submit(images)
        assert h.wait(30.0) and h.ok
    expected = direct_predictions((model, state.params), images)
    np.testing.assert_array_equal(h.predictions, expected)


def test_load_params_only_drops_int8_residuals(tmp_path):
    """ISSUE 11 satellite: a post-PR-10 checkpoint carrying the int8 wire
    codec's `residuals` subtree (plus sharded-layout opt state) must load
    params-only cleanly — residuals dropped, params bit-exact — and serve
    end-to-end via from_checkpoint."""
    from tpu_dp.checkpoint import CheckpointManager, load_params_only
    from tpu_dp.models import build_model
    from tpu_dp.parallel.quant import init_residuals
    from tpu_dp.train import SGD, create_train_state, shard_optimizer

    model = build_model("net")
    opt = shard_optimizer(SGD(momentum=0.9), 8)
    state = create_train_state(
        model, jax.random.PRNGKey(7),
        np.zeros((1, 32, 32, 3), np.float32), opt,
    )
    # The int8-trained shape: per-quantizable-leaf [world, qpad] residuals.
    state = state.replace(residuals=init_residuals(state.params, 8))
    assert state.residuals, "int8 net model must have quantizable leaves"
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        mgr.save(state, {"config": {"model": {"name": "net"}}}, step=3)

    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    params, batch_stats, meta = load_params_only(
        tmp_path / "step_0000000003", variables["params"]
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(state.params),
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert batch_stats == {}

    engine = InferenceEngine.from_checkpoint(
        tmp_path, buckets=(1, 2), slo_ms=500.0
    )
    rng = np.random.default_rng(5)
    images = rng.integers(0, 256, size=(2, 32, 32, 3)).astype(np.uint8)
    with engine:
        h = engine.submit(images)
        assert h.wait(30.0) and h.ok
    np.testing.assert_array_equal(
        h.predictions, direct_predictions((model, state.params), images)
    )


def test_engine_hot_swap_stamps_versions_and_drops_nothing(net_model):
    """Hot weight swap on the single-replica engine: applied between
    batches, every response stamped with the version that served it,
    post-swap predictions match the new weights, zero sheds."""
    model, params = net_model
    fresh = model.init(
        jax.random.PRNGKey(42), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    rng = np.random.default_rng(6)
    images = rng.integers(0, 256, size=(2, 32, 32, 3)).astype(np.uint8)
    engine = make_engine(net_model)
    with engine:
        h1 = engine.submit(images)
        assert h1.wait(30.0) and h1.ok and h1.model_version == 1
        v = engine.swap_model(fresh["params"])
        assert v == 2
        # The pending swap applies before the next dispatched batch.
        h2 = engine.submit(images)
        assert h2.wait(30.0) and h2.ok
        assert h2.model_version == 2
    np.testing.assert_array_equal(
        h1.predictions, direct_predictions(net_model, images)
    )
    np.testing.assert_array_equal(
        h2.predictions,
        direct_predictions((model, fresh["params"]), images),
    )
    assert engine.retraces == 0  # a swap is a data change, not a shape one
    # Two swaps published between the same pair of batches get DISTINCT
    # versions — stamps identify weights, not apply events.
    assert engine.swap_model(params) == 3
    assert engine.swap_model(fresh["params"]) == 4


def test_engine_swap_from_checkpoint_accepts_manager_root(tmp_path,
                                                          net_model):
    """swap_from_checkpoint resolves a CheckpointManager root exactly
    like from_checkpoint does (newest complete checkpoint)."""
    from tpu_dp.checkpoint import CheckpointManager
    from tpu_dp.models import build_model
    from tpu_dp.train import SGD, create_train_state

    model = build_model("net")
    state = create_train_state(
        model, jax.random.PRNGKey(21),
        np.zeros((1, 32, 32, 3), np.float32), SGD(momentum=0.9),
    )
    with CheckpointManager(tmp_path, async_save=False) as mgr:
        mgr.save(state, {"config": {"model": {"name": "net"}}}, step=7)
    rng = np.random.default_rng(8)
    images = rng.integers(0, 256, size=(2, 32, 32, 3)).astype(np.uint8)
    engine = make_engine(net_model)
    with engine:
        assert engine.swap_from_checkpoint(tmp_path) == 2  # root, not step dir
        h = engine.submit(images)
        assert h.wait(30.0) and h.ok and h.model_version == 2
    np.testing.assert_array_equal(
        h.predictions, direct_predictions((model, state.params), images)
    )


# -- meter satellite --------------------------------------------------------

def test_meter_mark_credits_variable_batch_sizes():
    """Serve metering: batch sizes vary per bucket and are credited at the
    fence (mark), not at dispatch — including the window-opening batch,
    whose execution lands inside the window."""
    from tpu_dp.utils import ThroughputMeter

    m = ThroughputMeter(warmup_steps=1)
    m.step(0)        # warmup dispatch: opens the window
    m.mark(8)        # its fence is in-window → its 8 images count
    m.step(0)
    time.sleep(0.002)
    m.mark(2)
    m.step(0)
    time.sleep(0.002)
    last = m.mark(32)
    assert m.elapsed > 0 and m._last == last
    assert m.images_per_sec == pytest.approx((8 + 2 + 32) / m.elapsed)
    # Warmup fences (window not open) are never credited.
    m.reset()
    assert m.mark(100) and m.images_per_sec == 0.0


def test_meter_plain_mark_keeps_training_semantics():
    """mark() without images must behave exactly as before (the trainer's
    fence): extends the window, credits nothing."""
    from tpu_dp.utils import ThroughputMeter

    m = ThroughputMeter(warmup_steps=1)
    m.step(10)
    m.step(10)
    dispatch_elapsed = m.elapsed
    time.sleep(0.002)
    m.mark()
    assert m.elapsed > dispatch_elapsed
    assert m.images_per_sec == pytest.approx(10 / m.elapsed)


# -- config + loadgen plumbing ---------------------------------------------

def test_serve_config_roundtrip_and_overrides():
    from tpu_dp.config import Config

    cfg = Config()
    cfg.override("serve.buckets", "1,2,4")
    cfg.override("serve.slo_ms", "25.5")
    cfg.override("serve.max_queue", "64")
    cfg.override("serve.replicas", "2")
    cfg.override("serve.class_slo_ms", "50,100")
    cfg.override("serve.class_floors", "0:0.9")
    cfg.override("serve.stale_after_s", "1.5")
    d = cfg.to_dict()
    assert d["serve"]["buckets"] == "1,2,4"
    cfg2 = Config.from_dict(d)
    assert cfg2.serve.slo_ms == 25.5 and cfg2.serve.max_queue == 64
    assert cfg2.serve.replicas == 2 and cfg2.serve.stale_after_s == 1.5
    assert cfg2.serve.class_slo_ms == "50,100"


def test_parse_class_slo_and_floors():
    from tpu_dp.config import parse_class_floors, parse_class_slo_ms

    assert parse_class_slo_ms("") == {}
    assert parse_class_slo_ms("50,100,250") == {0: 50.0, 1: 100.0, 2: 250.0}
    with pytest.raises(ValueError):
        parse_class_slo_ms("50,x")
    assert parse_class_floors("") == {}
    assert parse_class_floors("0:0.9,2:0.5") == {0: 0.9, 2: 0.5}
    with pytest.raises(ValueError):
        parse_class_floors("0=0.9")


def test_engine_from_serve_config(net_model):
    from tpu_dp.config import ServeConfig

    model, params = net_model
    engine = InferenceEngine.from_serve_config(
        model, params, ServeConfig(buckets="1,4", slo_ms=99.0)
    )
    assert engine.ladder.buckets == (1, 4)
    assert engine.slo_ms == 99.0


def test_arrival_offsets_patterns():
    rng = np.random.default_rng(0)
    pois = arrival_offsets(50, "poisson", 100.0, 8, rng)
    assert len(pois) == 50 and (np.diff(pois) >= 0).all() and pois[0] == 0
    burst = arrival_offsets(20, "burst", 100.0, 5, rng)
    # Groups of 5 share an arrival time; gaps between groups hold the rate.
    assert (burst[:5] == burst[0]).all()
    assert burst[5] > burst[4]
    assert len(arrival_offsets(0, "poisson", 100.0, 8, rng)) == 0
    with pytest.raises(ValueError):
        arrival_offsets(5, "steady", 100.0, 8, rng)
    with pytest.raises(ValueError):
        arrival_offsets(5, "poisson", 0.0, 8, rng)
