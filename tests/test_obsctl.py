"""obsctl — the post-hoc forensic CLI (`python -m tpu_dp.obs`, ISSUE 9).

Two layers of evidence: a REAL guard-rollback run (obs=full) whose
artifacts the timeline / merge-trace / diff commands must reconstruct
with no duplicate replayed-step events, and synthetic multi-rank
artifact trees that pin the cross-source merge (metrics + quarantine +
membership ledger + flight dumps + per-membership-epoch heartbeats) and
the eviction-story ordering. Plus the Prometheus textfile exporter.
"""

import json
import time
from pathlib import Path

import pytest

from tpu_dp.obs import obsctl

pytestmark = pytest.mark.obs


# -- a real rollback run (shared fixture) ----------------------------------

@pytest.fixture(scope="module")
def rollback_run(tmp_path_factory):
    """One guard spike-rollback run at obs=full: a 1e6x loss spike at
    step 8 triggers rewind to the step-5 snapshot and a replay — real
    rollback generations in every artifact."""
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    tmp = tmp_path_factory.mktemp("obsctl_run")
    cfg = Config()
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_train_size = 128
    cfg.data.synthetic_test_size = 16
    cfg.data.batch_size = 4
    cfg.data.device_resident = "off"
    cfg.train.epochs = 2
    cfg.train.log_every = 1000
    cfg.train.eval_at_end = False
    cfg.train.steps_per_call = 1
    cfg.train.ckpt_dir = str(tmp / "ck")
    cfg.train.ckpt_async = False
    cfg.train.obs = "full"
    cfg.parallel.num_devices = 1
    cfg.guard.enabled = True
    cfg.guard.action = "rollback"
    cfg.guard.spike_min_steps = 4
    cfg.guard.spike_z = 12
    cfg.resilience.snapshot_every_steps = 5
    cfg.resilience.fault = "spike:step=8,scale=1e6"
    Trainer(cfg).fit()
    return tmp / "ck"


def test_timeline_reconstructs_rollback_story(rollback_run, capsys):
    rc = obsctl.main(["timeline", str(rollback_run), "--json", "--steps"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    events, stats = out["events"], out["stats"]
    # Ordered by wall clock.
    assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)
    kinds = [e["kind"] for e in events]
    # The story: spike detected -> rollback -> tombstone -> replay ->
    # completion, all from the artifacts directory alone.
    assert "guard_spike" in kinds
    assert "guard_rollback" in kinds
    assert "guard_tombstone" in kinds
    assert kinds.index("guard_spike") < kinds.index("guard_rollback")
    assert "epoch_complete" in kinds and "exit" in kinds
    exits = [e for e in events if e["kind"] == "exit"]
    assert any(e["detail"]["reason"] == "clean" for e in exits)
    # No duplicate replayed-step events: the rollback replayed steps
    # 6..8, yet each optimizer step appears EXACTLY once (the surviving
    # generation), and the dedup is visible in the stats.
    steps = [e["step"] for e in events if e["kind"] == "step"]
    assert len(steps) == len(set(steps))
    assert stats["steps"]["replayed_beats_deduped"] > 0
    assert stats["steps"]["distinct"] == len(steps)
    # Replayed steps carry the surviving generation stamp.
    replayed = [e for e in events
                if e["kind"] == "step" and e.get("gen") == 1]
    assert replayed, "replay attempt did not win the dedup"
    # Swept per-step metrics: no rolled-back generation-0 record above
    # the rewind point survives into the timeline's metrics view.
    rb = next(e for e in events if e["kind"] == "guard_rollback")
    to_step = rb["detail"]["to_step"]
    assert all(e["step"] <= to_step for e in events
               if e["kind"] == "step" and not e.get("gen"))


def test_merge_trace_spans_generations_with_markers(rollback_run, tmp_path,
                                                    capsys):
    from tpu_dp.obs.export import validate_trace

    out_path = tmp_path / "merged.json"
    rc = obsctl.main(["merge-trace", str(rollback_run), "-o",
                      str(out_path)])
    assert rc == 0
    trace = json.loads(out_path.read_text())
    assert validate_trace(trace) == []
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    # Rollback generation 1 renders as its own track group.
    assert any("[gen 1]" in n for n in names)
    # Eviction/rollback-class markers are instant events.
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert any(e["name"] == "guard_rollback" for e in instants)


def test_diff_clean_vs_regressed_exit_codes(rollback_run, tmp_path, capsys):
    base = tmp_path / "base.json"
    assert obsctl.main(["diff", str(rollback_run),
                        "--write-baseline", str(base)]) == 0
    payload = json.loads(base.read_text())
    assert payload["goodput"] is not None and payload["p95_ms"] is not None
    capsys.readouterr()

    # Clean: the run against its own baseline.
    assert obsctl.main(["diff", str(rollback_run), "--baseline",
                        str(base), "--json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["regressed"] is False and verdict["compared"] >= 2

    # Synthetically regressed: the baseline demands a p95 this run
    # exceeds by >tolerance -> nonzero exit, CI gate trips.
    tampered = dict(payload, p95_ms=payload["p95_ms"] / 10.0)
    base.write_text(json.dumps(tampered))
    assert obsctl.main(["diff", str(rollback_run), "--baseline",
                        str(base), "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    bad = [c for c in verdict["checks"] if c["verdict"] == "regressed"]
    assert [c["signal"] for c in bad] == ["p95_ms"]

    # A BENCH_*.json-shaped baseline (latency.p95_ms) parses too.
    bench_shape = {"mfu": None, "goodput": payload["goodput"],
                   "latency": {"p95_ms": payload["p95_ms"]}}
    base.write_text(json.dumps(bench_shape))
    assert obsctl.main(["diff", str(rollback_run), "--baseline",
                        str(base)]) == 0

    # Nothing comparable on both sides: refuse to certify (exit 2).
    base.write_text(json.dumps({"note": "no signals"}))
    assert obsctl.main(["diff", str(rollback_run), "--baseline",
                        str(base)]) == 2
    # Missing run dir: usage error, not a traceback.
    assert obsctl.main(["timeline", str(tmp_path / "nope")]) == 2


# -- synthetic multi-rank artifacts (cross-source merge) -------------------

def _beat(d, rank, step, ts, step_ms=10.0, gen=None):
    rec = {"rank": rank, "step": step, "ts": ts, "step_ms": step_ms}
    if gen:
        rec["gen"] = gen
    path = d / f"heartbeat_r{rank:05d}.jsonl"
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


@pytest.fixture()
def sdc_artifacts(tmp_path):
    """A hand-built 3-rank SDC-eviction artifact tree: divergence
    detected -> suspect attributed -> eviction -> rollback regroup ->
    completion, spread across every source obsctl merges."""
    run = tmp_path / "run"
    obs = run / "obs"
    obs.mkdir(parents=True)
    t0 = 1000.0

    def iso(ts):
        from datetime import datetime, timezone

        return datetime.fromtimestamp(ts, timezone.utc).isoformat(
            timespec="milliseconds")

    # me0: 3 ranks, steps 1..3.
    for rank in range(3):
        for step in (1, 2, 3):
            _beat(obs, rank, step, t0 + step)
    # me1 (post-eviction, world 2, reassigned ranks): replays 2..5.
    me1 = obs / "me0001"
    me1.mkdir()
    for rank in range(2):
        for step in (2, 3, 4, 5):
            _beat(me1, rank, step, t0 + 20 + step)

    (run / "metrics.jsonl").write_text("\n".join([
        json.dumps({"ts": iso(t0 + 4), "step": 3, "schema": 3,
                    "event": "guard_sdc", "suspects": [2], "majority":
                    "a1b2"}),
        json.dumps({"ts": iso(t0 + 30), "step": 2, "schema": 3,
                    "event": "elastic_regroup", "membership_epoch": 1,
                    "flavor": "rollback", "world": 2}),
        json.dumps({"ts": iso(t0 + 40), "step": 5, "schema": 3,
                    "epoch": 1, "loss": 1.5, "accuracy": 0.5}),
    ]) + "\n")
    (run / "quarantine.jsonl").write_text(json.dumps({
        "kind": "sdc", "ts": t0 + 4, "rollback_generation": 0, "step": 3,
        "suspects": [2],
    }) + "\n")

    gen_dir = run / "membership" / "gen_0000000000_w3_abc"
    gen_dir.mkdir(parents=True)
    (gen_dir / "epoch_0000.json").write_text(json.dumps({
        "schema": 1, "epoch": 0, "members": [0, 1, 2], "world": 3,
        "coordinator": None, "departed": [], "resume": None,
        "reason": "initial", "ts": t0,
    }))
    (gen_dir / "epoch_0001.json").write_text(json.dumps({
        "schema": 1, "epoch": 1, "members": [0, 1], "world": 2,
        "coordinator": None,
        "departed": [{"sid": 2, "reason": "sdc audit mismatch at step 3"}],
        "resume": {"epoch": 0, "steps_done": 1}, "reason": "rollback",
        "ts": t0 + 10,
    }))

    # The victim's black box (stable rank 2): eviction decision + exit.
    (obs / "flightrec_r00002.json").write_text(json.dumps({
        "schema": 1, "rank": 2, "reason": "PreemptedError: evicted",
        "ts": t0 + 15, "run": {}, "total_recorded": 3, "counters": {},
        "events": [
            # Same replicated verdict the metrics stream already tells:
            # must dedupe to ONE guard_sdc event, not world+1 copies.
            {"ts": t0 + 4.2, "kind": "guard_sdc", "step": 3,
             "suspects": [2], "majority": "a1b2"},
            {"ts": t0 + 4.5, "kind": "guard_evict", "step": 3, "rank": 2,
             "reason": "sdc audit suspect"},
            {"ts": t0 + 14, "kind": "elastic_departure", "step": 3},
        ],
    }))
    return run


def test_timeline_orders_the_eviction_story(sdc_artifacts, capsys):
    rc = obsctl.main(["timeline", str(sdc_artifacts), "--json", "--steps"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    events = out["events"]
    kinds = [e["kind"] for e in events]
    story = ["guard_sdc", "guard_evict", "eviction", "elastic_regroup",
             "epoch_complete"]
    positions = [kinds.index(k) for k in story]
    assert positions == sorted(positions), (
        f"story out of order: {list(zip(story, positions))}"
    )
    # One replicated verdict, told once: the metrics/quarantine/dump
    # copies of the same guard_sdc decision merged (metrics wins).
    sdc_events = [e for e in events if e["kind"] == "guard_sdc"]
    assert len(sdc_events) == 1 and sdc_events[0]["source"] == "metrics"
    ev = next(e for e in events if e["kind"] == "eviction")
    assert ev["rank"] == 2 and "sdc" in ev["detail"]["reason"]
    # The victim's exit reason survives from its dump.
    ex = next(e for e in events if e["kind"] == "exit")
    assert "evicted" in ex["detail"]["reason"]
    # Replayed steps (2, 3 ran in me0 AND me1) appear once each, from
    # the me1 attempt; the sweep count is reported.
    steps = sorted(e["step"] for e in events if e["kind"] == "step")
    assert steps == [1, 2, 3, 4, 5]
    me_of = {e["step"]: e["detail"]["me"] for e in events
             if e["kind"] == "step"}
    assert me_of[2] == 1 and me_of[3] == 1 and me_of[1] == 0
    assert out["stats"]["steps"]["replayed_beats_deduped"] > 0
    # membership sources were all found
    assert out["stats"]["sources"]["membership"] is True
    assert out["stats"]["sources"]["flightrec_dumps"] == 1


def test_stragglers_leave_one_out_attribution(tmp_path, capsys):
    obs = tmp_path / "run" / "obs"
    obs.mkdir(parents=True)
    now = time.time()
    for rank in range(3):
        for step in (1, 2, 3):
            ms = 200.0 if (rank == 1 and step == 2) else 10.0
            _beat(obs, rank, step, now + step, step_ms=ms)
    rc = obsctl.main(["stragglers", str(tmp_path / "run"), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)["stragglers"]
    assert report[0]["world"] == 3
    issues = report[0]["issues"]
    assert [(i["rank"], i["step"]) for i in issues] == [(1, 2)]
    assert issues[0]["ratio"] >= 3.0


def test_merge_trace_synthetic_pids_per_membership_epoch(sdc_artifacts,
                                                         tmp_path, capsys):
    from tpu_dp.obs.export import validate_trace

    out_path = tmp_path / "t.json"
    assert obsctl.main(["merge-trace", str(sdc_artifacts), "-o",
                        str(out_path)]) == 0
    trace = json.loads(out_path.read_text())
    assert validate_trace(trace) == []
    pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    # me0 ranks 0..2 -> pids 0..2; me1 ranks 0..1 -> pids 1000..1001.
    assert {0, 1, 2, 1000, 1001} <= pids
    instants = {e["name"] for e in trace["traceEvents"] if e["ph"] == "i"}
    assert "eviction" in instants and "elastic_regroup" in instants


# -- promfile --------------------------------------------------------------

def test_promfile_write_parse_roundtrip(tmp_path):
    from tpu_dp.obs.counters import Counters
    from tpu_dp.obs.promfile import parse_promfile, write_promfile

    reg = Counters()
    reg.inc("retry.attempts", 3)
    reg.gauge("obs.mfu", 0.42)
    reg.gauge("serve.device_util.b8", 0.3)
    out = write_promfile(tmp_path / "m.prom", registry=reg,
                         labels={"rank": "1"})
    assert not list(tmp_path.glob("*.tmp*"))  # atomic
    parsed = parse_promfile(out.read_text())
    assert parsed["tpu_dp_retry_attempts"]["type"] == "counter"
    assert parsed["tpu_dp_obs_mfu"]["type"] == "gauge"
    (label, value), = parsed["tpu_dp_obs_mfu"]["samples"].items()
    assert 'rank="1"' in label and value == 0.42
    assert parsed["tpu_dp_serve_device_util_b8"]["samples"][label] == 0.3


def test_counters_snapshot_typed_split():
    from tpu_dp.obs.counters import Counters

    reg = Counters()
    reg.inc("a.count")
    reg.gauge("b.gauge", 2.0)
    counts, gauges = reg.snapshot_typed()
    assert counts == {"a.count": 1.0} and gauges == {"b.gauge": 2.0}
    # The flat snapshot stays the union (back-compat).
    assert reg.snapshot() == {"a.count": 1.0, "b.gauge": 2.0}
