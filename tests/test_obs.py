"""tpu_dp.obs — spans, counters, heartbeats/straggler detection, export.

Unit coverage for each obs piece plus Trainer integration on the
8-virtual-device CPU mesh: the acceptance contract is that a
``train.obs=full`` run produces schema-2 per-step `metrics.jsonl` records
carrying all four span fields and a counter snapshot, a Perfetto JSON
that validates against the trace-event schema, and heartbeat files a
`HealthMonitor` can attribute stragglers from — while ``obs=off`` leaves
the metrics log per-epoch-only and creates no telemetry dir at all.
The cross-process straggler test lives in `test_multiprocess.py`.
"""

import json
import signal
import time
from datetime import datetime

import pytest

from tpu_dp.obs import (
    Counters,
    HealthError,
    HealthMonitor,
    HeartbeatWriter,
    SpanRecorder,
    counters as global_counters,
    export_perfetto,
    merge_traces,
    percentile,
    to_trace_events,
    validate_trace,
)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _isolate_global_counters():
    """Tests share the process-wide registry; snapshot/restore around each."""
    saved_counts = dict(global_counters._counts)
    saved_gauges = dict(global_counters._gauges)
    global_counters.reset()
    yield
    global_counters._counts.clear()
    global_counters._counts.update(saved_counts)
    global_counters._gauges.clear()
    global_counters._gauges.update(saved_gauges)


# ---------------------------------------------------------------- spans --

def test_percentile_interpolates():
    vals = sorted(float(v) for v in range(1, 101))  # 1..100
    assert percentile(vals, 50) == pytest.approx(50.5)
    assert percentile(vals, 95) == pytest.approx(95.05)
    assert percentile(vals, 0) == 1.0
    assert percentile(vals, 100) == 100.0
    assert percentile([7.0], 99) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


def test_span_recorder_ring_and_rollup():
    rec = SpanRecorder(capacity=50)
    for step in range(1, 101):  # 100 records into a 50-slot ring
        rec.record(step, {"dispatch": float(step)}, ts=1000.0 + step)
    assert len(rec) == 50 and rec.total_recorded == 100
    records = rec.records()
    # Ring keeps the newest 50 (steps 51..100), oldest first.
    assert records[0]["step"] == 51 and records[-1]["step"] == 100
    roll = rec.rollup()["dispatch"]
    assert roll["n"] == 50 and roll["max"] == 100.0
    assert roll["p50"] == pytest.approx(75.5)
    assert roll["mean"] == pytest.approx(75.5)
    assert roll["p99"] == pytest.approx(percentile(
        [float(v) for v in range(51, 101)], 99), abs=1e-3)


def test_span_recorder_window_attribution():
    rec = SpanRecorder()
    recs = rec.record_window(11, 4, {"dispatch": 40.0, "device": 8.0},
                             ts=500.0)
    assert [r["step"] for r in recs] == [11, 12, 13, 14]
    assert all(r["spans"] == {"dispatch": 10.0, "device": 2.0} for r in recs)
    # Per-step start times advance by the window's per-step share.
    assert recs[1]["ts"] - recs[0]["ts"] == pytest.approx(0.012)


def test_span_recorder_rejects_bad_capacity():
    with pytest.raises(ValueError):
        SpanRecorder(capacity=0)


# -------------------------------------------------------------- counters --

def test_counters_inc_gauge_snapshot_reset():
    c = Counters()
    c.inc("a")
    c.inc("a", 2.5)
    c.gauge("g", 7.0)
    c.gauge("g", 9.0)  # last write wins
    assert c.get("a") == 3.5 and c.get("g") == 9.0
    assert c.get("absent", -1.0) == -1.0
    assert c.snapshot() == {"a": 3.5, "g": 9.0}
    c.reset()
    assert c.snapshot() == {}


def test_retry_call_publishes_counters():
    from tpu_dp.resilience.retry import retry_call

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    assert retry_call(flaky, retries=3, sleep=lambda s: None) == "ok"
    snap = global_counters.snapshot()
    assert snap["retry.attempts"] == 3.0
    assert snap["retry.retries"] == 2.0
    assert "retry.exhausted" not in snap

    def doomed():
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError):
        retry_call(doomed, retries=1, sleep=lambda s: None)
    assert global_counters.get("retry.exhausted") == 1.0


def test_recompile_guard_publishes_retraces():
    from tpu_dp.analysis.recompile import RecompileGuard

    cache = {"size": 1}

    def fake_step():
        return None

    fake_step._cache_size = lambda: cache["size"]
    guard = RecompileGuard(fake_step, name="t", warmup_calls=1,
                           on_retrace="warn", logger=lambda m: None)
    guard()
    guard()             # baseline stable
    cache["size"] = 3   # two retraces
    guard()
    assert guard.retraces == 2
    assert global_counters.get("recompile.retraces") == 2.0


def test_snapshot_manager_publishes_seconds(tmp_path):
    import numpy as np

    from tpu_dp.resilience import SnapshotManager

    state = {"w": np.ones((4, 4), np.float32), "b": np.zeros(3, np.float32)}
    snap = SnapshotManager(tmp_path / "snaps", every_steps=0, keep=2)
    assert snap.snapshot(state, 7, {"t": 1}) is not None
    snap.wait()
    snap.close()
    s = global_counters.snapshot()
    assert s["snapshot.writes"] == 1.0
    assert s["snapshot.write_s"] > 0.0
    assert s["snapshot.wait_s"] >= 0.0


def test_preemption_handler_counts_signals():
    from tpu_dp.resilience import PreemptionHandler

    h = PreemptionHandler()
    h._handle(signal.SIGTERM, None)  # direct: no real signal needed
    h._handle(signal.SIGTERM, None)
    assert h.requested
    assert global_counters.get("preempt.signals") == 2.0


def test_device_memory_gauges_absent_is_not_zero():
    # The CPU backend has no memory_stats: the gauge must be ABSENT (never
    # a fake 0 that reads as "no memory in use").
    from tpu_dp.obs import update_device_memory_gauges

    reg = Counters()
    written = update_device_memory_gauges(reg)
    snap = reg.snapshot()
    for name in snap:
        assert snap[name] > 0.0
    assert set(written) == set(snap)


# ---------------------------------------------------------------- health --

def _write_beats(run_dir, rank, beats):
    with HeartbeatWriter(run_dir, rank=rank) as hb:
        for step, step_ms, ts in beats:
            hb.beat(step, step_ms, ts=ts)


def test_heartbeat_writer_throttles_by_crossing(tmp_path):
    hb = HeartbeatWriter(tmp_path, rank=0, every_steps=5)
    # Window boundaries 3, 6, 9, 12: crossings of 5 are at 6 and 12 —
    # equality never happens, crossing must still beat.
    accepted = [hb.beat(s, 1.0) for s in (3, 6, 9, 12)]
    hb.close()
    assert accepted == [True, True, False, True]
    lines = hb.path.read_text().splitlines()
    assert [json.loads(l)["step"] for l in lines] == [3, 6, 12]


def test_health_monitor_flags_straggler_and_reports(tmp_path):
    now = time.time()
    _write_beats(tmp_path, 0, [(5, 10.0, now)])
    _write_beats(tmp_path, 1, [(5, 11.0, now)])
    _write_beats(tmp_path, 2, [(5, 50.0, now)])  # 5x the median
    _write_beats(tmp_path, 3, [(5, 9.0, now)])
    mon = HealthMonitor(tmp_path, world=4, straggler_factor=3.0,
                        stale_after_s=60.0)
    issues = mon.check(now=now)
    assert [(i.kind, i.rank) for i in issues] == [("straggler", 2)]
    # Leave-one-out median: rank 2 is judged against median(10, 11, 9).
    assert issues[0].ratio >= 3.0 and issues[0].median_ms == 10.0
    # warn mode logs through the injected logger and returns the issues.
    logged = []
    warn_mon = HealthMonitor(tmp_path, world=4, logger=logged.append)
    assert warn_mon.report(warn_mon.check(now=now)) == issues
    assert len(logged) == 1 and "rank 2" in logged[0]


def test_health_monitor_stale_and_missing(tmp_path):
    now = time.time()
    _write_beats(tmp_path, 0, [(8, 10.0, now)])
    _write_beats(tmp_path, 1, [(8, 10.0, now - 120.0)])  # went quiet
    mon = HealthMonitor(tmp_path, world=3, stale_after_s=60.0)
    # Startup grace: immediately after construction a rank with no file
    # yet is NOT "missing" (the first check can precede any rank's first
    # compile-heavy window) — only the genuinely stale rank flags.
    assert {(i.kind, i.rank) for i in mon.check(now=now)} == {("stale", 1)}
    mon._start = now - 120.0  # grace elapsed: rank 2 never appeared
    issues = mon.check(now=now)
    kinds = {(i.kind, i.rank) for i in issues}
    assert ("stale", 1) in kinds and ("missing", 2) in kinds
    stale = next(i for i in issues if i.kind == "stale")
    assert stale.age_s == pytest.approx(120.0, abs=1.0)
    # raise mode: HealthError carries the issues for the supervisor.
    strict = HealthMonitor(tmp_path, world=3, stale_after_s=60.0,
                           on_flag="raise")
    with pytest.raises(HealthError) as exc_info:
        strict.report(strict.check(now=now))
    assert any(i.kind == "stale" and i.rank == 1
               for i in exc_info.value.issues)


def test_health_monitor_joiner_admission_grace(tmp_path):
    """A freshly admitted rank (elastic grow) has no heartbeat history
    and must not be flagged "missing" against the MONITOR's start time —
    `admit` restarts its grace from the admission moment (ISSUE 12
    satellite; regression for the joiner-compiles-first-window gap)."""
    now = time.time()
    _write_beats(tmp_path, 0, [(8, 10.0, now)])
    _write_beats(tmp_path, 1, [(8, 10.0, now)])
    mon = HealthMonitor(tmp_path, world=3, stale_after_s=60.0)
    mon._start = now - 300.0  # global startup grace long elapsed
    # Without admission bookkeeping, rank 2 flags missing...
    assert {(i.kind, i.rank) for i in mon.check(now=now)} == {("missing", 2)}
    # ...but an admission NOW restarts its personal grace window:
    mon.admit(2, ts=now - 5.0)
    assert mon.check(now=now) == []
    # The grace is per-rank and finite: once the joiner's own grace
    # elapses with still no beat, it flags again — with the age measured
    # from ADMISSION, not from the monitor's birth.
    issues = mon.check(now=now + 100.0)
    missing = [i for i in issues if i.kind == "missing"]
    assert [(i.kind, i.rank) for i in missing] == [("missing", 2)]
    assert missing[0].age_s == pytest.approx(105.0, abs=1.0)
    # A beat from the admitted rank clears it like any other.
    _write_beats(tmp_path, 2, [(9, 10.0, now + 100.0)])
    assert not [i for i in mon.check(now=now + 100.0)
                if i.kind == "missing"]


def test_health_monitor_stale_scales_with_window_duration(tmp_path):
    """Beats arrive once per dispatched window; a window longer than the
    fixed threshold must not mark a healthy, still-beating rank as hung.
    Staleness is judged against STALE_INTERVAL_FACTOR x the rank's own
    observed inter-beat interval when that exceeds stale_after_s."""
    now = time.time()
    # 70s windows (beats 70s apart), checked 80s after the last beat:
    # within 3 x 70s — healthy, not stale — despite stale_after_s=60.
    _write_beats(tmp_path, 0, [(8, 70_000.0, now - 150.0),
                               (16, 70_000.0, now - 80.0)])
    _write_beats(tmp_path, 1, [(8, 70_000.0, now - 150.0),
                               (16, 70_000.0, now - 80.0)])
    mon = HealthMonitor(tmp_path, world=2, stale_after_s=60.0)
    assert mon.check(now=now) == []
    # Past 3x the interval the rank really is gone.
    assert {(i.kind, i.rank) for i in mon.check(now=now + 200.0)} == {
        ("stale", 0), ("stale", 1)}


def test_health_monitor_scan_attributes_past_steps(tmp_path):
    now = time.time()
    # Rank 1 was slow at step 3 only; latest beats look healthy — check()
    # sees nothing, scan() still attributes the historical straggle.
    _write_beats(tmp_path, 0, [(s, 10.0, now) for s in (1, 2, 3, 4)])
    _write_beats(tmp_path, 1, [(1, 10.0, now), (2, 10.0, now),
                               (3, 400.0, now), (4, 10.0, now)])
    mon = HealthMonitor(tmp_path, world=2, straggler_factor=3.0,
                        stale_after_s=3600.0)
    assert mon.check(now=now) == []
    issues = mon.scan()
    assert [(i.kind, i.rank, i.step) for i in issues] == [("straggler", 1, 3)]
    assert issues[0].ratio >= 3.0


def test_health_monitor_min_step_ms_floor(tmp_path):
    # µs-scale steps: 3x jitter on a 0.2ms median must not flag.
    now = time.time()
    _write_beats(tmp_path, 0, [(1, 0.2, now)])
    _write_beats(tmp_path, 1, [(1, 0.7, now)])
    mon = HealthMonitor(tmp_path, world=2, straggler_factor=3.0,
                        min_step_ms=1.0, stale_after_s=60.0)
    assert mon.check(now=now) == []


def test_health_monitor_latest_reads_only_the_tail(tmp_path, monkeypatch):
    """The live check is O(world), not O(history): latest() must find the
    newest beat through a bounded tail read even when the heartbeat file
    has grown far past the tail window."""
    now = time.time()
    _write_beats(tmp_path, 0, [(s, 10.0, now) for s in range(1, 2001)])
    monkeypatch.setattr(HealthMonitor, "TAIL_BYTES", 512)
    mon = HealthMonitor(tmp_path, world=1)
    assert mon.latest()[0]["step"] == 2000
    # scan() deliberately keeps the full history (post-hoc attribution).
    assert len(mon.read_beats()[0]) == 2000


def test_health_monitor_skips_torn_lines(tmp_path):
    _write_beats(tmp_path, 0, [(1, 10.0, time.time())])
    with open(tmp_path / "heartbeat_r00000.jsonl", "a") as f:
        f.write('{"rank": 0, "step"')  # torn mid-write by a dying host
    mon = HealthMonitor(tmp_path, world=1)
    assert mon.latest()[0]["step"] == 1


def test_health_monitor_validates_config(tmp_path):
    with pytest.raises(ValueError):
        HealthMonitor(tmp_path, world=2, on_flag="explode")
    with pytest.raises(ValueError):
        HealthMonitor(tmp_path, world=2, straggler_factor=1.0)


def test_straggler_detection_via_fault_injector(tmp_path, monkeypatch):
    """The deterministic delay fault drives the detector single-process:
    two simulated ranks share a run dir, rank 1 carries
    ``delay:step=3,rank=1`` — scan() must name exactly that rank/step."""
    from tpu_dp.resilience.faultinject import FaultInjector

    monkeypatch.setenv("TPU_DP_FAULT", "delay:step=3,rank=1,ms=200")
    for rank in (0, 1):
        inj = FaultInjector.from_spec("", rank=rank)
        with HeartbeatWriter(tmp_path, rank=rank) as hb:
            for step in range(1, 6):
                t0 = time.perf_counter()
                time.sleep(0.02)
                inj.on_step(step)
                hb.beat(step, (time.perf_counter() - t0) * 1e3)
    mon = HealthMonitor(tmp_path, world=2, straggler_factor=3.0,
                        stale_after_s=3600.0)
    stragglers = [i for i in mon.scan() if i.kind == "straggler"]
    assert stragglers, "injected delay not flagged"
    worst = max(stragglers, key=lambda i: i.ratio)
    # The worst offender is the injected rank at the injected step,
    # carrying the measured lag factor and the delay itself.
    assert (worst.rank, worst.step) == (1, 3)
    assert worst.ratio >= 3.0
    assert worst.step_ms >= 200.0


# ---------------------------------------------------------------- export --

def _sample_records():
    return [
        {"step": 1, "ts": 100.0,
         "spans": {"data_wait": 2.0, "h2d": 0.5, "dispatch": 1.0,
                   "device": 8.0}},
        {"step": 2, "ts": 100.02,
         "spans": {"data_wait": 1.0, "h2d": 0.4, "dispatch": 0.9,
                   "device": 7.5}},
    ]


def test_to_trace_events_schema_and_layout():
    trace = to_trace_events(
        _sample_records(), rank=3,
        counter_points=[{"ts": 101.0, "counters": {"retry.attempts": 2.0,
                                                   "note": "skipped"}}],
    )
    assert validate_trace(trace) == []
    events = trace["traceEvents"]
    slices = [e for e in events if e["ph"] == "X"]
    assert len(slices) == 8 and all(e["pid"] == 3 for e in slices)
    # Spans lay out back-to-back from the step's start.
    s1 = [e for e in slices if e["args"]["step"] == 1]
    assert s1[0]["name"] == "data_wait" and s1[0]["ts"] == 100.0 * 1e6
    assert s1[1]["ts"] == pytest.approx(s1[0]["ts"] + s1[0]["dur"])
    # Metadata names the rank process and each span track.
    meta = {(e["name"], e["args"]["name"]) for e in events if e["ph"] == "M"}
    assert ("process_name", "tpu_dp rank 3") in meta
    assert ("thread_name", "device") in meta
    # Counter events carry numeric values only.
    cs = [e for e in events if e["ph"] == "C"]
    assert [c["name"] for c in cs] == ["retry.attempts"]


def test_export_perfetto_writes_valid_json(tmp_path):
    out = export_perfetto(tmp_path / "nested" / "trace.json",
                          _sample_records(), rank=0)
    trace = json.loads(out.read_text())
    assert validate_trace(trace) == []
    assert not list(tmp_path.glob("**/*.tmp"))  # atomic rename, no residue


def test_merge_traces_keeps_all_events():
    a = to_trace_events(_sample_records(), rank=0)
    b = to_trace_events(_sample_records(), rank=1)
    merged = merge_traces([a, b])
    assert validate_trace(merged) == []
    assert len(merged["traceEvents"]) == (
        len(a["traceEvents"]) + len(b["traceEvents"])
    )


def test_validate_trace_catches_malformed():
    assert validate_trace([]) != []
    assert validate_trace({"traceEvents": "nope"}) != []
    bad_ph = {"traceEvents": [{"ph": "Z", "name": "x"}]}
    assert "unknown ph" in validate_trace(bad_ph)[0]
    missing = {"traceEvents": [{"ph": "X", "name": "x", "ts": 1.0}]}
    assert any("missing" in e for e in validate_trace(missing))
    negative = {"traceEvents": [
        {"ph": "X", "name": "x", "ts": -1.0, "dur": 1.0, "pid": 0, "tid": 0}
    ]}
    assert any("non-negative" in e for e in validate_trace(negative))


# ------------------------------------------------------------- profiling --

def test_parse_profile_steps():
    from tpu_dp.utils import parse_profile_steps

    assert parse_profile_steps("") is None
    assert parse_profile_steps(None) is None
    assert parse_profile_steps("400:450") == (400, 450)
    for bad in ("400", "400:", ":450", "5:5", "9:4", "-1:4", "a:b"):
        with pytest.raises(ValueError):
            parse_profile_steps(bad)


def _drive_profiler(prof, windows):
    """Simulate the trainer's hooks over (first_step, n) windows."""
    for first, n in windows:
        prof.on_window_start(first, n)
        prof.on_step(first + n - 1)


def test_step_profiler_traces_exactly_the_requested_steps():
    from tpu_dp.utils import StepProfiler

    events = []
    prof = StepProfiler("/tmp/x", 10, 20,
                        start_fn=lambda d: events.append(("start", d)),
                        stop_fn=lambda: events.append(("stop",)))
    windows = [(s, 4) for s in (1, 5, 9, 13, 17, 21, 25)]  # w4 boundaries
    traced = []
    for first, n in windows:
        prof.on_window_start(first, n)
        if prof.active:
            traced.extend(range(first, first + n))
        prof.on_step(first + n - 1)
    assert events == [("start", "/tmp/x"), ("stop",)]
    assert prof.done and not prof.active
    # The realized trace covers the requested [10, 20) — snapped outward
    # to window boundaries, never shifted one window late.
    assert set(range(10, 20)) <= set(traced)
    prof.on_window_start(40, 4)  # one artifact per run: never re-arms
    prof.on_step(43)
    assert len(events) == 2


def test_step_profiler_single_step_and_in_window_ranges():
    from tpu_dp.utils import StepProfiler

    # profile_steps=3:4 at steps_per_call=1 must trace step 3 itself.
    events = []
    prof = StepProfiler("/tmp/x", 3, 4,
                        start_fn=lambda d: events.append("start"),
                        stop_fn=lambda: events.append("stop"))
    for step in (1, 2, 3, 4):
        prof.on_window_start(step, 1)
        armed_for = step if prof.active and len(events) == 1 else None
        if armed_for is not None:
            assert armed_for == 3  # armed BEFORE step 3 ran, not after
        prof.on_step(step)
    assert events == ["start", "stop"]
    # A range strictly inside one dispatch window still traces (snaps
    # outward) instead of being skipped.
    events2 = []
    prof2 = StepProfiler("/tmp/x", 2, 5,
                         start_fn=lambda d: events2.append("start"),
                         stop_fn=lambda: events2.append("stop"))
    _drive_profiler(prof2, [(1, 8), (9, 8)])
    assert events2 == ["start", "stop"] and prof2.done


def test_step_profiler_close_stops_open_trace():
    from tpu_dp.utils import StepProfiler

    events = []
    prof = StepProfiler("/tmp/x", 0, 100,
                        start_fn=lambda d: events.append("start"),
                        stop_fn=lambda: events.append("stop"))
    prof.on_window_start(1, 1)
    prof.on_step(1)
    prof.close()  # training ended inside the range
    assert events == ["start", "stop"]
    prof_skipped = StepProfiler("/tmp/x", 5, 6,
                                start_fn=lambda d: events.append("start2"),
                                stop_fn=lambda: events.append("stop2"))
    prof_skipped.on_window_start(50, 1)  # resumed past the range
    assert prof_skipped.done and "start2" not in events
    with pytest.raises(ValueError):
        StepProfiler("", 0, 10)


# ----------------------------------------------------------- integration --

def _obs_cfg(tmp_path, **overrides):
    from tpu_dp.config import Config

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 64
    c.data.synthetic_test_size = 16
    c.data.batch_size = 16
    c.data.prefetch = 1
    c.train.epochs = 1
    c.train.log_every = 2
    c.train.eval_at_end = False
    c.train.ckpt_dir = str(tmp_path / "ck")
    for k, v in overrides.items():
        section, field = k.split(".")
        setattr(getattr(c, section), field, v)
    return c


def _read_metrics(path):
    return [json.loads(l) for l in path.read_text().splitlines()]


def test_trainer_obs_full_end_to_end(tmp_path):
    """The acceptance contract: obs=full on the CPU mesh produces schema-2
    per-step records with all four spans + counter snapshots, heartbeats,
    and a Perfetto JSON that validates."""
    from tpu_dp.train.trainer import Trainer

    cfg = _obs_cfg(tmp_path, **{"train.obs": "full"})
    tr = Trainer(cfg)
    tr.fit()

    records = _read_metrics(tmp_path / "ck" / "metrics.jsonl")
    assert all(r["schema"] == 3 for r in records)
    for r in records:  # ts parses as ISO-8601
        datetime.fromisoformat(r["ts"])
    per_step = [r for r in records if "spans" in r and "epoch" not in r]
    assert [r["step"] for r in per_step] == [1, 2, 3, 4]
    for r in per_step:
        assert set(r["spans"]) == {"data_wait", "h2d", "dispatch", "device"}
        assert r["spans"]["device"] > 0.0  # full mode fences per window
        assert isinstance(r["counters"], dict)
    epoch_rec = next(r for r in records if "epoch" in r)
    assert set(epoch_rec["spans"]) == {"data_wait", "h2d", "dispatch",
                                       "device"}
    assert {"p50", "p95", "p99", "mean", "max", "n"} <= set(
        epoch_rec["spans"]["dispatch"])

    # Heartbeats: one file for this rank, one line per step.
    beats = (tmp_path / "ck" / "obs" / "heartbeat_r00000.jsonl")
    assert len(beats.read_text().splitlines()) == 4

    # Perfetto export validates and covers the run's steps.
    trace = json.loads(
        (tmp_path / "ck" / "obs" / "trace.perfetto.json").read_text())
    assert validate_trace(trace) == []
    steps_in_trace = {e["args"]["step"] for e in trace["traceEvents"]
                      if e["ph"] == "X"}
    assert steps_in_trace == {1, 2, 3, 4}

    # The run summary block exists and rolls up the same spans.
    summary = tr.obs_summary()
    assert summary["mode"] == "full"
    assert summary["spans_ms"]["device"]["n"] == 4


def test_trainer_obs_off_is_untelemetered(tmp_path):
    from tpu_dp.train.trainer import Trainer

    tr = Trainer(_obs_cfg(tmp_path))
    tr.fit()
    records = _read_metrics(tmp_path / "ck" / "metrics.jsonl")
    # Schema stamps are unconditional (the satellite fix)…
    assert all(r["schema"] == 3 and "ts" in r and "step" in r
               for r in records)
    # …but there are no per-step records, no spans, and no live-telemetry
    # artifacts — the only obs-dir inhabitant at obs=off is the
    # always-on flight-recorder dump (crash forensics are deliberately
    # NOT gated by train.obs; docs/OBSERVABILITY.md "Flight recorder").
    assert [r for r in records if "spans" in r] == []
    assert [p.name for p in (tmp_path / "ck" / "obs").iterdir()] == [
        "flightrec_r00000.json"
    ]
    assert tr.obs_summary() is None


def test_trainer_obs_basic_spans_without_sync(tmp_path):
    from tpu_dp.train.trainer import Trainer

    cfg = _obs_cfg(tmp_path, **{"train.obs": "basic"})
    tr = Trainer(cfg)
    tr.fit()
    records = _read_metrics(tmp_path / "ck" / "metrics.jsonl")
    # Basic: no per-step records (those are full-mode), and the epoch
    # rollup OMITS h2d/device (unmeasured — basic adds no fence; absence,
    # never a fake zero) while data_wait/dispatch are real.
    assert [r for r in records if "spans" in r and "epoch" not in r] == []
    epoch_rec = next(r for r in records if "epoch" in r)
    assert set(epoch_rec["spans"]) == {"data_wait", "dispatch"}
    assert epoch_rec["spans"]["dispatch"]["max"] > 0.0
    # Heartbeats + export still on.
    assert (tmp_path / "ck" / "obs" / "trace.perfetto.json").exists()
    assert (tmp_path / "ck" / "obs" / "heartbeat_r00000.jsonl").exists()


def test_trainer_metrics_path_configurable(tmp_path):
    from tpu_dp.train.trainer import Trainer

    sink = tmp_path / "elsewhere" / "m.jsonl"
    cfg = _obs_cfg(tmp_path, **{"train.metrics_path": str(sink)})
    Trainer(cfg).fit()
    assert sink.exists()
    assert not (tmp_path / "ck" / "metrics.jsonl").exists()
    assert any("epoch" in r for r in _read_metrics(sink))


def test_trainer_rejects_bad_obs_mode(tmp_path):
    from tpu_dp.train.trainer import Trainer

    with pytest.raises(ValueError, match="train.obs"):
        Trainer(_obs_cfg(tmp_path, **{"train.obs": "loud"}))


def test_trainer_profile_steps_requires_dir(tmp_path):
    from tpu_dp.train.trainer import Trainer

    with pytest.raises(ValueError, match="profile_dir"):
        Trainer(_obs_cfg(tmp_path, **{"train.profile_steps": "1:3"}))


def test_config_obs_roundtrip_and_cli():
    from tpu_dp.config import Config, parse_cli

    cfg = parse_cli(["--train.obs=full", "--obs.straggler_factor=4.5",
                     "--obs.on_straggler=raise", "--train.metrics_path=/x",
                     "--train.profile_steps=10:20"])
    assert cfg.train.obs == "full"
    assert cfg.obs.straggler_factor == 4.5
    assert cfg.obs.on_straggler == "raise"
    rebuilt = Config.from_dict(cfg.to_dict())
    assert rebuilt.obs.straggler_factor == 4.5
    assert rebuilt.train.profile_steps == "10:20"
