"""Optimizer parity — our SGD must match torch's update rule step-for-step.

The reference uses `optim.SGD(lr=0.001, momentum=0.9)`
(`cifar_example.py:64`); SURVEY.md §4 Unit calls for "SGD+momentum step math"
verification. torch (CPU) is in the build env, so we check against the real
thing on random pytrees.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.train.optim import SGD


def _torch_trajectory(torch, arrays, grads_seq, lr, momentum, wd):
    params = [torch.nn.Parameter(torch.tensor(a)) for a in arrays]
    opt = torch.optim.SGD(params, lr=lr, momentum=momentum, weight_decay=wd)
    out = []
    for grads in grads_seq:
        opt.zero_grad()
        for p, g in zip(params, grads):
            p.grad = torch.tensor(g)
        opt.step()
        out.append([p.detach().numpy().copy() for p in params])
    return out


@pytest.mark.parametrize("momentum,wd", [(0.9, 0.0), (0.0, 0.0), (0.9, 5e-4)])
def test_sgd_matches_torch(momentum, wd):
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    arrays = [rng.normal(size=(4, 3)).astype(np.float32),
              rng.normal(size=(7,)).astype(np.float32)]
    grads_seq = [
        [rng.normal(size=a.shape).astype(np.float32) for a in arrays]
        for _ in range(4)
    ]
    expected = _torch_trajectory(torch, arrays, grads_seq, 0.01, momentum, wd)

    sgd = SGD(momentum=momentum, weight_decay=wd)
    params = [jnp.asarray(a) for a in arrays]
    opt_state = sgd.init(params)
    for step, grads in enumerate(grads_seq):
        params, opt_state = sgd.update(
            [jnp.asarray(g) for g in grads], opt_state, params, 0.01
        )
        for ours, ref in zip(params, expected[step]):
            np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-6, atol=2e-6)


def test_cross_entropy_matches_torch():
    """`cross_entropy_loss` vs `nn.CrossEntropyLoss` (`cifar_example.py:63`)."""
    torch = pytest.importorskip("torch")
    from tpu_dp.train.step import cross_entropy_loss

    rng = np.random.default_rng(1)
    logits = rng.normal(size=(16, 10)).astype(np.float32) * 3
    labels = rng.integers(0, 10, size=16)
    ours = float(cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels)))
    ref = float(
        torch.nn.CrossEntropyLoss()(torch.tensor(logits), torch.tensor(labels))
    )
    assert ours == pytest.approx(ref, rel=1e-5)


def test_weight_decay_exclusion_mask():
    """decay_exclude_bias_and_norm: bias/scale leaves get no L2 pull."""
    import jax

    params = {
        "conv": {"kernel": jnp.ones((2, 2))},
        "norm": {"scale": jnp.ones((2,)), "bias": jnp.ones((2,))},
        "dense": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
    }
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)

    opt = SGD(momentum=0.0, weight_decay=0.1, decay_exclude_bias_and_norm=True)
    new_params, _ = opt.update(grads, opt.init(params), params, lr=1.0)

    # Zero grads: kernels shrink by lr*wd*p = 0.1, excluded leaves unchanged.
    np.testing.assert_allclose(np.asarray(new_params["conv"]["kernel"]), 0.9)
    np.testing.assert_allclose(np.asarray(new_params["dense"]["kernel"]), 0.9)
    np.testing.assert_allclose(np.asarray(new_params["norm"]["scale"]), 1.0)
    np.testing.assert_allclose(np.asarray(new_params["norm"]["bias"]), 1.0)
    np.testing.assert_allclose(np.asarray(new_params["dense"]["bias"]), 1.0)

    # Default (torch parity): everything decays.
    opt_all = SGD(momentum=0.0, weight_decay=0.1)
    all_params, _ = opt_all.update(grads, opt_all.init(params), params, lr=1.0)
    np.testing.assert_allclose(np.asarray(all_params["norm"]["scale"]), 0.9)
