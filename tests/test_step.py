"""Compiled-step tests — the DDP-equivalence property and training dynamics.

SURVEY.md §4: "N-device grads == single-device grads on the concatenated
batch" is *the* correctness property of gradient-averaging data parallelism
(what DDP's allreduce guarantees, `cifar_example_ddp.py:83`), and loss
decrease is the reference's only in-band training signal
(`cifar_example.py:84-87`).
"""

import jax
import numpy as np
import pytest

from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import Net
from tpu_dp.train import (
    SGD,
    constant_lr,
    create_train_state,
    make_eval_step,
    make_train_step,
)


def _make_batch(seed, n):
    ds = make_synthetic(n, 10, seed=seed, name="synthetic")
    return {"image": normalize(ds.images), "label": ds.labels}


def _copy(state):
    # The train step donates its input state; tests that reuse a state
    # across two step functions must pass fresh buffers.
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.array, state)


@pytest.fixture(scope="module")
def setup():
    model = Net()
    opt = SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    return model, opt, state


def test_dp_equivalence_8_vs_1(setup, mesh8, mesh1):
    """Same global batch ⇒ same updated params on a 1-mesh and an 8-mesh."""
    model, opt, state = setup
    batch = _make_batch(0, 16)

    step8 = make_train_step(model, opt, mesh8, constant_lr(0.01))
    step1 = make_train_step(model, opt, mesh1, constant_lr(0.01))

    s8, m8 = step8(_copy(state), batch)
    s1, m1 = step1(_copy(state), batch)

    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-5)
    assert int(m8["correct"]) == int(m1["correct"])
    for a, b in zip(
        jax.tree_util.tree_leaves(s8.params), jax.tree_util.tree_leaves(s1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_compiled_dp_step_contains_gradient_allreduce(setup, mesh8, mesh1):
    """The DDP guarantee must exist as a real collective in the compiled
    program, not merely as numerical equivalence: the GSPMD partitioner
    must have inserted an all-reduce (the NCCL-allreduce analogue the
    reference gets from DDP's reducer, `cifar_example_ddp.py:83`,
    SURVEY.md §2B) into the 8-device program — and the 1-device program
    must contain none (nothing to reduce across)."""
    model, opt, state = setup
    batch = _make_batch(0, 16)
    # (.lower only traces avals — no execution, no donation, no copy needed)
    hlo8 = (make_train_step(model, opt, mesh8, constant_lr(0.05))
            .lower(state, batch).compile().as_text())
    # Specifically the GRADIENT all-reduce, not just any collective (the
    # sharded-batch metric means also lower to all-reduces): XLA emits the
    # grads as a bucketed tuple all-reduce whose operands are param-shaped —
    # conv1's kernel grad f32[5,5,3,6] must sit on an all-reduce line.
    grad_ar = [l for l in hlo8.splitlines()
               if "all-reduce(" in l and "f32[5,5,3,6]" in l]
    assert grad_ar, "no param-shaped (gradient) all-reduce in 8-device HLO"
    hlo1 = (make_train_step(model, opt, mesh1, constant_lr(0.05))
            .lower(state, batch).compile().as_text())
    assert "all-reduce" not in hlo1


def test_multi_step_trajectory_equivalence(setup, mesh8, mesh1):
    """Replicas stay in lockstep over several steps (momentum included)."""
    model, opt, state = setup
    step8 = make_train_step(model, opt, mesh8, constant_lr(0.05))
    step1 = make_train_step(model, opt, mesh1, constant_lr(0.05))
    s8, s1 = _copy(state), _copy(state)
    for i in range(3):
        batch = _make_batch(i, 8)
        s8, _ = step8(s8, batch)
        s1, _ = step1(s1, batch)
    for a, b in zip(
        jax.tree_util.tree_leaves(s8.params), jax.tree_util.tree_leaves(s1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_shard_map_matches_gspmd(setup, mesh8):
    """Explicit-collectives path ≡ GSPMD-inferred path, step for step.

    Two statements of the same distributed program — per-shard grads +
    explicit `lax.pmean` vs sharding annotations + inferred all-reduce —
    must produce identical losses, counts, and parameter trajectories.
    """
    from tpu_dp.train import make_train_step_shard_map

    model, opt, state = setup
    step_g = make_train_step(model, opt, mesh8, constant_lr(0.05))
    step_s = make_train_step_shard_map(model, opt, mesh8, constant_lr(0.05))
    sg, ss = _copy(state), _copy(state)
    for i in range(3):
        batch = _make_batch(i, 16)
        sg, mg = step_g(sg, batch)
        ss, ms = step_s(ss, batch)
        np.testing.assert_allclose(
            float(mg["loss"]), float(ms["loss"]), rtol=1e-5
        )
        assert int(mg["correct"]) == int(ms["correct"])
        assert int(mg["count"]) == int(ms["count"])
    for a, b in zip(
        jax.tree_util.tree_leaves(sg.params), jax.tree_util.tree_leaves(ss.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_shard_map_accum_matches_gspmd(setup, mesh8):
    """Explicit-collectives path under gradient accumulation ≡ GSPMD path.

    The one reduction must sit after the microbatch scan (the invariant
    `tpu_dp.analysis` DP202 verifies statically); numerically that means
    the accum shard_map step tracks the accum GSPMD step exactly.
    """
    from tpu_dp.train import make_train_step_shard_map

    model, opt, state = setup
    step_g = make_train_step(model, opt, mesh8, constant_lr(0.05),
                             accum_steps=2)
    step_s = make_train_step_shard_map(model, opt, mesh8, constant_lr(0.05),
                                       accum_steps=2)
    sg, ss = _copy(state), _copy(state)
    for i in range(2):
        flat = _make_batch(i, 32)
        batch = {
            "image": flat["image"].reshape(2, 16, 32, 32, 3),
            "label": flat["label"].reshape(2, 16),
        }
        sg, mg = step_g(sg, batch)
        ss, ms = step_s(ss, batch)
        np.testing.assert_allclose(
            float(mg["loss"]), float(ms["loss"]), rtol=1e-5
        )
        assert int(mg["correct"]) == int(ms["correct"])
        assert int(mg["count"]) == int(ms["count"])
    for a, b in zip(
        jax.tree_util.tree_leaves(sg.params), jax.tree_util.tree_leaves(ss.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_shard_map_sync_bn_resnet(mesh8):
    """shard_map path with a BatchNorm model (axis_name-synced stats)."""
    from tpu_dp.models import ResNet18
    from tpu_dp.parallel.dist import DATA_AXIS
    from tpu_dp.train import make_train_step_shard_map

    model_s = ResNet18(num_classes=10, num_filters=8, axis_name=DATA_AXIS)
    model_g = ResNet18(num_classes=10, num_filters=8)
    opt = SGD(momentum=0.9)
    state = create_train_state(
        model_g, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step_g = make_train_step(model_g, opt, mesh8, constant_lr(0.05))
    step_s = make_train_step_shard_map(model_s, opt, mesh8, constant_lr(0.05))
    sg, ss = _copy(state), _copy(state)
    batch = _make_batch(0, 16)
    sg, mg = step_g(sg, batch)
    ss, ms = step_s(ss, batch)
    np.testing.assert_allclose(float(mg["loss"]), float(ms["loss"]), rtol=1e-5)
    # Global-batch BN statistics: running stats from per-shard stats synced
    # over the data axis must match GSPMD's global-batch computation.
    for a, b in zip(
        jax.tree_util.tree_leaves(sg.batch_stats),
        jax.tree_util.tree_leaves(ss.batch_stats),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(sg.params), jax.tree_util.tree_leaves(ss.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_loss_decreases(setup, mesh8):
    """The reference's in-band signal: running loss goes down."""
    model, opt, state = setup
    step = make_train_step(model, opt, mesh8, constant_lr(0.05))
    state = _copy(state)
    ds = make_synthetic(512, 10, seed=1, name="synthetic")
    losses = []
    for i in range(20):
        sel = slice((i * 64) % 512, (i * 64) % 512 + 64)
        batch = {
            "image": normalize(ds.images[sel]),
            "label": ds.labels[sel],
        }
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.05


def test_step_counter_and_lr(setup, mesh8):
    model, opt, state = setup
    step = make_train_step(model, opt, mesh8, constant_lr(0.01))
    batch = _make_batch(0, 8)
    state = _copy(state)
    prev_step = int(state.step)
    s1, m = step(state, batch)
    assert int(s1.step) == prev_step + 1
    assert float(m["lr"]) == pytest.approx(0.01)


def test_eval_step_counts(setup, mesh8):
    model, opt, state = setup
    ev = make_eval_step(model, mesh8)
    batch = _make_batch(0, 24)
    m = ev(state, batch)
    assert int(m["count"]) == 24
    assert 0 <= int(m["correct"]) <= 24


def test_scanned_multi_step_matches_host_loop(setup, mesh8):
    """K scanned steps (one dispatch) ≡ K host-loop step calls, exactly.

    `make_multi_step` is the device-side training loop (lax.scan over the
    step body); its trajectory, per-step losses, and LR schedule positions
    must be indistinguishable from driving `make_train_step` from the host.
    """
    import jax.numpy as jnp

    from tpu_dp.train import cosine_lr, make_multi_step

    model, opt, state = setup
    K, n = 4, 16
    sched = cosine_lr(0.05, 10, 2)
    step = make_train_step(model, opt, mesh8, sched)
    loop = make_multi_step(model, opt, mesh8, sched, num_steps=K)

    batches = [_make_batch(100 + i, n) for i in range(K)]
    pool = {
        "image": np.stack([b["image"] for b in batches]),
        "label": np.stack([b["label"] for b in batches]),
    }

    s_host = _copy(state)
    host_metrics = []
    for b in batches:
        s_host, m = step(s_host, b)
        host_metrics.append(m)

    s_scan, stacked = loop(_copy(state), pool)

    assert int(s_scan.step) == int(s_host.step)
    for i, m in enumerate(host_metrics):
        np.testing.assert_allclose(
            float(stacked["loss"][i]), float(m["loss"]), rtol=1e-5
        )
        assert int(stacked["correct"][i]) == int(m["correct"])
        np.testing.assert_allclose(
            float(stacked["lr"][i]), float(m["lr"]), rtol=1e-6
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(s_scan.params),
        jax.tree_util.tree_leaves(s_host.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_resident_loop_matches_multi_step(setup, mesh8):
    """Device-resident feed ≡ streaming feed, exactly.

    `make_multi_step_resident` gathers each step's batch on-device from the
    staged dataset by index; the trajectory and per-step metrics must be
    indistinguishable from `make_multi_step` on the equivalent stacked pool
    (VERDICT r4 next-steps #3). Exercises uint8 staging: normalization
    happens in-body for both paths.
    """
    from tpu_dp.parallel.sharding import replicated_sharding, shard_batch
    from tpu_dp.train import cosine_lr, make_multi_step
    from tpu_dp.train.step import make_multi_step_resident

    model, opt, state = setup
    K, n = 4, 16
    sched = cosine_lr(0.05, 10, 2)
    ds = make_synthetic(K * n, 10, seed=7, name="res")

    loop = make_multi_step(model, opt, mesh8, sched, num_steps=K)
    pool = {
        "image": ds.images.reshape(K, n, 32, 32, 3),  # uint8: in-body norm
        "label": ds.labels.reshape(K, n),
    }
    s_stream, stream_m = loop(_copy(state), pool)

    rloop = make_multi_step_resident(model, opt, mesh8, sched, num_steps=K)
    data = shard_batch({"image": ds.images, "label": ds.labels}, mesh8,
                       spec=replicated_sharding(mesh8))
    # Shuffled indices covering the same examples in the same step order.
    idx = np.arange(K * n, dtype=np.int32).reshape(K, n)
    s_res, res_m = rloop(_copy(state), data, idx)

    assert int(s_res.step) == int(s_stream.step) == K
    np.testing.assert_allclose(np.asarray(res_m["loss"]),
                               np.asarray(stream_m["loss"]), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res_m["correct"]),
                                  np.asarray(stream_m["correct"]))
    for a, b in zip(
        jax.tree_util.tree_leaves(s_res.params),
        jax.tree_util.tree_leaves(s_stream.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_resident_loop_with_accum(setup, mesh8):
    """Scan-of-scan over the resident feed: (window, accum, batch) indices."""
    from tpu_dp.parallel.sharding import replicated_sharding, shard_batch
    from tpu_dp.train import constant_lr
    from tpu_dp.train.step import make_multi_step_resident

    model, opt, state = setup
    ds = make_synthetic(64, 10, seed=8, name="res")
    data = shard_batch({"image": ds.images, "label": ds.labels}, mesh8,
                       spec=replicated_sharding(mesh8))

    ref = make_train_step(model, opt, mesh8, constant_lr(0.05), accum_steps=2)
    s_ref = _copy(state)
    for j in range(2):
        lo = j * 32
        s_ref, _ = ref(s_ref, {
            "image": normalize(ds.images[lo:lo + 32]).reshape(2, 16, 32, 32, 3),
            "label": ds.labels[lo:lo + 32].reshape(2, 16),
        })

    rloop = make_multi_step_resident(model, opt, mesh8, constant_lr(0.05),
                                     num_steps=2, accum_steps=2)
    idx = np.arange(64, dtype=np.int32).reshape(2, 2, 16)
    s_res, m = rloop(_copy(state), data, idx)

    assert int(s_res.step) == 2
    assert int(m["count"][0]) == 32
    for a, b in zip(
        jax.tree_util.tree_leaves(s_res.params),
        jax.tree_util.tree_leaves(s_ref.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_scanned_loop_modular_pool_matches_host_loop(setup, mesh8):
    """Pool-cycling branch (pool < num_steps) ≡ host loop cycling batches.

    This is the exact path bench.py measures (4-slot pool, 30-step window):
    the in-program modular gather must feed batch i % pool to step i.
    """
    from tpu_dp.train import cosine_lr, make_multi_step

    model, opt, state = setup
    K, pool_n, n = 6, 3, 16
    sched = cosine_lr(0.05, 10, 2)
    step = make_train_step(model, opt, mesh8, sched)
    loop = make_multi_step(model, opt, mesh8, sched, num_steps=K)

    batches = [_make_batch(200 + i, n) for i in range(pool_n)]
    pool = {
        "image": np.stack([b["image"] for b in batches]),
        "label": np.stack([b["label"] for b in batches]),
    }

    s_host = _copy(state)
    host_losses = []
    for i in range(K):
        s_host, m = step(s_host, batches[i % pool_n])
        host_losses.append(float(m["loss"]))

    s_scan, stacked = loop(_copy(state), pool)

    assert int(s_scan.step) == K
    np.testing.assert_allclose(
        np.asarray(stacked["loss"]), np.asarray(host_losses), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s_scan.params),
        jax.tree_util.tree_leaves(s_host.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
