"""Trainer integration tests on the 8-virtual-device CPU mesh.

SURVEY.md §4 Integration: "short-run CIFAR-10 train on synthetic/cached data
asserting loss decreases … and checkpoint round-trip". Exercises the full
`main()`-equivalent path: config → data → mesh → compiled steps → epochs →
eval → checkpoint → resume.
"""

import jax
import numpy as np
import pytest

from tpu_dp.config import Config
from tpu_dp.train.trainer import Trainer


def _tiny_cfg(tmp_path, **overrides) -> Config:
    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 256
    c.data.synthetic_test_size = 64
    c.data.batch_size = 32
    c.data.prefetch = 1
    c.train.epochs = 2
    c.train.log_every = 4
    c.train.ckpt_dir = str(tmp_path / "ck")
    c.optim.lr = 0.05
    for k, v in overrides.items():
        section, field = k.split(".")
        setattr(getattr(c, section), field, v)
    return c


def test_fit_trains_and_evaluates(tmp_path, capsys):
    trainer = Trainer(_tiny_cfg(tmp_path))
    result = trainer.fit()
    assert len(result["history"]) == 2
    # Loss decreases across epochs (the reference's in-band signal).
    assert result["history"][1]["loss"] < result["history"][0]["loss"]
    assert "eval" in result and 0.0 <= result["eval"]["accuracy"] <= 1.0
    out = capsys.readouterr().out
    assert "Finished Training" in out  # reference print parity
    assert "loss:" in out
    # Checkpoints (manager layout: step dirs + latest pointer) + final weights.
    step_dirs = sorted((tmp_path / "ck").glob("step_*/state.msgpack"))
    assert step_dirs, "no step checkpoints written"
    assert (tmp_path / "ck" / "latest").exists()
    assert (tmp_path / "ck" / "final_params.msgpack").exists()


def test_resume_continues_from_checkpoint(tmp_path):
    t1 = Trainer(_tiny_cfg(tmp_path))
    t1.fit()
    steps_after_first = int(t1.state.step)
    assert steps_after_first == 2 * 8  # 2 epochs × (256/32) steps

    cfg2 = _tiny_cfg(tmp_path)
    cfg2.train.resume = True
    cfg2.train.epochs = 3
    t2 = Trainer(cfg2)
    assert t2.start_epoch == 2
    assert int(t2.state.step) == steps_after_first
    result = t2.fit()
    assert len(result["history"]) == 1  # only the one remaining epoch ran
    assert int(t2.state.step) == 3 * 8


def test_eval_partial_batch_exact_counts(tmp_path):
    # 64 test examples with batch 48 → final batch has 16 real + 32 padded;
    # exact-count eval must still see exactly 64 examples.
    cfg = _tiny_cfg(tmp_path)
    cfg.data.batch_size = 48
    cfg.data.synthetic_train_size = 96
    trainer = Trainer(cfg)
    trainer.fit()
    acc_total = 0
    for batch in trainer.test_pipe:
        m = trainer.eval_step(trainer.state, batch)
        acc_total += int(m["count"])
    assert acc_total == 64


def test_num_classes_conflict_raises(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    cfg.model.num_classes = 7
    with pytest.raises(ValueError, match="conflicts"):
        Trainer(cfg)


def test_indivisible_batch_raises(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    cfg.data.batch_size = 12  # not divisible over the 8-device mesh
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(cfg)


def test_bf16_and_cosine_run(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    cfg.model.bf16 = True
    cfg.optim.schedule = "cosine"
    cfg.optim.warmup_epochs = 0.5
    cfg.train.epochs = 1
    result = Trainer(cfg).fit()
    assert np.isfinite(result["history"][0]["loss"])


def test_metrics_jsonl_written(tmp_path):
    import json

    trainer = Trainer(_tiny_cfg(tmp_path))
    trainer.fit()
    lines = (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()
    records = [json.loads(l) for l in lines]
    epochs = [r["epoch"] for r in records if "epoch" in r]
    assert epochs == [1, 2]
    assert any("eval" in r for r in records)


def test_steps_per_call_matches_per_step_trajectory(tmp_path, capsys):
    """Windowed dispatch (train.steps_per_call) ≡ plain per-step training.

    Same config, same seed: the scanned-window Trainer must produce the
    same epoch losses and the same reference-format prints (log boundaries
    fall inside windows), including the trailing per-step remainder
    (9 steps per epoch vs window 4 → 2 windows + 1 single).
    """

    def run(steps_per_call, tag):
        cfg = _tiny_cfg(tmp_path / tag)
        cfg.data.synthetic_train_size = 144  # 9 steps of 16
        cfg.data.batch_size = 16
        cfg.train.log_every = 2
        cfg.train.steps_per_call = steps_per_call
        tr = Trainer(cfg)
        res = tr.fit()
        return res, capsys.readouterr().out

    res1, out1 = run(1, "per_step")
    res4, out4 = run(4, "windowed")

    for a, b in zip(res1["history"], res4["history"]):
        assert a["loss"] == pytest.approx(b["loss"], rel=1e-5)
        assert a["accuracy"] == pytest.approx(b["accuracy"], rel=1e-5)
    # Identical reference-format print stream (same boundaries, same
    # values). Match the reference's "[epoch, step] loss:" shape so log0
    # lines (timestamped, also bracket-led) don't leak into the comparison.
    import re

    fmt = re.compile(r"\[\d+, +\d+\] loss:")
    lines1 = [l for l in out1.splitlines() if fmt.match(l)]
    lines4 = [l for l in out4.splitlines() if fmt.match(l)]
    assert lines1 and lines1 == lines4


def test_steps_per_call_auto(tmp_path):
    """steps_per_call=0 picks a window automatically (≤24, ≤steps/epoch)
    and still matches the per-step trajectory."""
    cfg = _tiny_cfg(tmp_path / "auto")
    cfg.data.synthetic_train_size = 128  # 4 steps of 32
    cfg.train.steps_per_call = 0
    tr = Trainer(cfg)
    assert tr.steps_per_call == 4  # min(24, steps_per_epoch)
    res = tr.fit()

    cfg1 = _tiny_cfg(tmp_path / "per_step")
    cfg1.data.synthetic_train_size = 128
    res1 = Trainer(cfg1).fit()
    assert len(res["history"]) == len(res1["history"]) == 2
    for a, b in zip(res["history"], res1["history"]):
        assert a["loss"] == pytest.approx(b["loss"], rel=1e-6)

    with pytest.raises(ValueError):
        cfg_neg = _tiny_cfg(tmp_path / "neg")
        cfg_neg.train.steps_per_call = -1
        Trainer(cfg_neg)

    # Auto falls back to per-step when windows are unavailable.
    cfg2 = _tiny_cfg(tmp_path / "auto_nodrop")
    cfg2.data.synthetic_train_size = 128
    cfg2.train.steps_per_call = 0
    cfg2.data.drop_remainder = False
    assert Trainer(cfg2).steps_per_call == 1


def test_device_resident_matches_streaming_trajectory(tmp_path):
    """data.device_resident=on ≡ off: same sampler order, same step body,
    same trajectory — only the feed mechanics differ (indices vs batches).
    Runs windowed with shuffle+augment to cover the full production shape.
    """

    def run(mode, tag):
        cfg = _tiny_cfg(tmp_path / tag)
        cfg.data.synthetic_train_size = 192
        cfg.data.batch_size = 16
        cfg.data.augment = True
        cfg.data.device_resident = mode
        cfg.train.steps_per_call = 4  # 12 steps → 3 windows
        return Trainer(cfg).fit()

    on = run("on", "resident")
    off = run("off", "streaming")
    for a, b in zip(on["history"], off["history"]):
        assert a["loss"] == pytest.approx(b["loss"], rel=1e-6)
        assert a["accuracy"] == pytest.approx(b["accuracy"], rel=1e-6)
    assert on["eval"]["accuracy"] == pytest.approx(
        off["eval"]["accuracy"], rel=1e-6)


def test_device_resident_auto_respects_budget(tmp_path):
    """auto stages only when the dataset fits resident_max_bytes."""
    cfg = _tiny_cfg(tmp_path / "auto_small")
    tr = Trainer(cfg)
    assert tr.resident_train is not None  # tiny synthetic set: staged

    cfg2 = _tiny_cfg(tmp_path / "auto_big")
    cfg2.data.resident_max_bytes = 1  # nothing fits
    tr2 = Trainer(cfg2)
    assert tr2.resident_train is None

    cfg3 = _tiny_cfg(tmp_path / "forced_off")
    cfg3.data.device_resident = "off"
    assert Trainer(cfg3).resident_train is None

    cfg4 = _tiny_cfg(tmp_path / "on_no_drop")
    cfg4.data.device_resident = "on"
    cfg4.data.drop_remainder = False
    with pytest.raises(ValueError):
        Trainer(cfg4)


def test_steps_per_call_composes_with_grad_accum(tmp_path):
    """Windowed dispatch × gradient accumulation (scan-of-scan) matches the
    per-step accumulation trajectory (VERDICT r4 next-steps #4) — BASELINE
    config 5's shape (big global batch via accumulation) running windowed.
    """

    def run(steps_per_call, tag):
        cfg = _tiny_cfg(tmp_path / tag)
        cfg.data.synthetic_train_size = 192  # 6 updates of 2×16 per epoch
        cfg.data.batch_size = 16
        cfg.optim.grad_accum_steps = 2
        cfg.train.steps_per_call = steps_per_call
        tr = Trainer(cfg)
        assert tr.global_batch_size == 32
        return tr.fit()

    res1 = run(1, "accum_per_step")
    res4 = run(4, "accum_windowed")  # 6 updates → 1 window of 4 + 2 singles

    for a, b in zip(res1["history"], res4["history"]):
        assert a["loss"] == pytest.approx(b["loss"], rel=1e-5)
        assert a["accuracy"] == pytest.approx(b["accuracy"], rel=1e-5)
