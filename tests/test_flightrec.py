"""Flight recorder (`tpu_dp.obs.flightrec`, ISSUE 9).

The acceptance property: EVERY exit path out of a training process —
clean completion, `PreemptedError` (self-injected SIGTERM), a real
external SIGTERM, `DivergedError`, and an unhandled exception — leaves
an atomic, schema-versioned ``flightrec_r<rank>.json`` whose event tail
matches the live metrics records; plus the ring/dump/sentinel unit
contracts.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from tpu_dp.obs import flightrec

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _fresh_recorder():
    flightrec.recorder.reset()
    yield
    flightrec.recorder.reset()


# -- ring / dump units -----------------------------------------------------

def test_ring_bounds_and_lifetime_count():
    fr = flightrec.FlightRecorder(capacity=4)
    for i in range(7):
        fr.record("step", step=i)
    assert len(fr) == 4 and fr.total_recorded == 7
    assert [e["step"] for e in fr.events()] == [3, 4, 5, 6]
    assert all(e["kind"] == "step" and e["ts"] > 0 for e in fr.events())


def test_dump_atomic_schema_and_roundtrip(tmp_path):
    fr = flightrec.FlightRecorder(capacity=8)
    fr.configure(rank=3, dump_dir=tmp_path, run={"model": "net"})
    fr.record("guard_trigger", step=5, trigger="spike")
    out = fr.dump(reason="unit test")
    assert out == tmp_path / "flightrec_r00003.json"
    assert not list(tmp_path.glob("*.tmp*"))  # atomic rename, no residue
    payload = flightrec.read_dump(out)
    assert payload["schema"] == flightrec.SCHEMA
    assert payload["rank"] == 3 and payload["reason"] == "unit test"
    assert payload["run"] == {"model": "net"}
    assert payload["events"][-1]["kind"] == "guard_trigger"
    assert isinstance(payload["counters"], dict)
    # A foreign schema is refused, never misread.
    bad = tmp_path / "flightrec_r00009.json"
    bad.write_text(json.dumps({"schema": 999}))
    with pytest.raises(ValueError, match="schema"):
        flightrec.read_dump(bad)


def test_dump_survives_numpy_fields(tmp_path):
    fr = flightrec.FlightRecorder()
    fr.configure(rank=0, dump_dir=tmp_path)
    fr.record("guard_sdc", step=2, suspects=[np.int64(2)],
              value=np.float32(1.5))
    payload = flightrec.read_dump(fr.dump(reason="numpy"))
    ev = payload["events"][-1]
    assert ev["suspects"] == [2] and ev["value"] == 1.5


def test_dump_without_target_returns_none():
    fr = flightrec.FlightRecorder()
    fr.record("step", step=1)
    assert fr.dump(reason="nowhere") is None  # never raises either


def test_configure_preserves_ring_across_rehome(tmp_path):
    fr = flightrec.FlightRecorder(capacity=8)
    fr.record("step", step=1)
    fr.configure(rank=1, dump_dir=tmp_path)
    assert [e["step"] for e in fr.events()] == [1]  # regroup keeps history
    fr.configure(rank=1, dump_dir=tmp_path, capacity=2)
    fr.record("step", step=2)
    fr.record("step", step=3)
    assert [e["step"] for e in fr.events()] == [2, 3]


def test_dump_request_sentinel_honored_once_per_write(tmp_path):
    fr = flightrec.FlightRecorder()
    fr.configure(rank=0, dump_dir=tmp_path)
    assert fr.poll_dump_request() is None  # no sentinel, one stat only
    flightrec.write_dump_request(tmp_path, "rank 1 heartbeat stale")
    out = fr.poll_dump_request()
    assert out is not None
    payload = flightrec.read_dump(out)
    assert "rank 1 heartbeat stale" in payload["reason"]
    assert fr.poll_dump_request() is None  # same sentinel: honored once
    time.sleep(0.01)
    flightrec.write_dump_request(tmp_path, "again")
    os.utime(tmp_path / flightrec.DUMP_REQUEST)  # ensure fresh mtime
    assert fr.poll_dump_request() is not None  # a new request re-dumps


def test_health_monitor_requests_dump_only_for_hangs(tmp_path):
    from tpu_dp.obs.health import HealthIssue, HealthMonitor

    mon = HealthMonitor(tmp_path, world=2)
    straggler = HealthIssue(kind="straggler", rank=1, step=3, ratio=4.0)
    assert mon.request_dump([straggler]) is None  # slow ≠ dead: no dump
    stale = HealthIssue(kind="stale", rank=1, step=3, age_s=120.0)
    sentinel = mon.request_dump([straggler, stale])
    assert Path(sentinel).name == flightrec.DUMP_REQUEST
    assert "rank 1" in json.loads(Path(sentinel).read_text())["reason"]


# -- exit paths ------------------------------------------------------------

_CLI_COMMON = [
    "--data.dataset=synthetic",
    "--data.synthetic_train_size=64",
    "--data.synthetic_test_size=16",
    "--data.batch_size=8",
    "--train.epochs=2",
    "--train.log_every=100",
    "--train.eval_at_end=false",
    "--train.obs=full",
    "--train.steps_per_call=1",
]


def _train_cmd(ckpt_dir, *extra):
    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("TPU_DP_FAULT", None)
    env["PYTHONPATH"] = (f"{repo}{os.pathsep}{env['PYTHONPATH']}"
                         if env.get("PYTHONPATH") else str(repo))
    return ([sys.executable, str(repo / "train.py"),
             f"--train.ckpt_dir={ckpt_dir}", *_CLI_COMMON, *extra],
            repo, env)


def _assert_blackbox(ckpt_dir, expect_reason):
    """The dump exists, parses, is schema-versioned, and its step-event
    tail matches the live metrics records (the last step the black box
    saw is the last step rank 0 logged)."""
    dump_path = Path(ckpt_dir) / "obs" / "flightrec_r00000.json"
    assert dump_path.exists(), "dead rank left no black box"
    payload = flightrec.read_dump(dump_path)  # parses + schema-checked
    assert expect_reason in payload["reason"]
    metrics = [json.loads(l) for l in
               (Path(ckpt_dir) / "metrics.jsonl").read_text().splitlines()]
    step_events = [e for e in payload["events"] if e["kind"] == "step"]
    per_step = [r for r in metrics if "spans" in r and "epoch" not in r]
    assert step_events and per_step
    assert step_events[-1]["step"] == per_step[-1]["step"]
    # The exit itself is the final recorded event.
    assert payload["events"][-1]["kind"] == "exit"
    assert expect_reason in payload["events"][-1]["reason"]
    return payload


@pytest.mark.parametrize("fault,extra,rc,reason", [
    # PreemptedError: the injector SIGTERMs self; the handler's boundary
    # raise runs the snapshot-exit-143 contract — and the dump.
    ("preempt:step=5", [], 143, "PreemptedError"),
    # DivergedError: a NaN loss under guard.action=halt exits 65.
    ("nan:step=3", ["--guard.enabled=true", "--guard.action=halt",
                    "--parallel.num_devices=1"], 65, "DivergedError"),
])
def test_dump_on_faulted_exit_paths(tmp_path, fault, extra, rc, reason):
    ckpt = tmp_path / "ck"
    argv, repo, env = _train_cmd(ckpt, f"--resilience.fault={fault}", *extra)
    proc = subprocess.run(argv, cwd=repo, env=env, capture_output=True,
                          text=True, timeout=240)
    assert proc.returncode == rc, proc.stdout + proc.stderr
    payload = _assert_blackbox(ckpt, reason)
    if reason == "PreemptedError":
        kinds = [e["kind"] for e in payload["events"]]
        # The handler stamped the signal AND the boundary stamped the exit
        # decision — the black box shows the causal chain, not just death.
        assert "preempt_signal" in kinds and "preempt_exit" in kinds
    if reason == "DivergedError":
        kinds = [e["kind"] for e in payload["events"]]
        assert "guard_trigger" in kinds and "guard_halt" in kinds


def test_dump_on_external_sigterm(tmp_path):
    """A REAL external SIGTERM (not the injector): the delay fault parks
    the run at a boundary long enough for the signal to land mid-train."""
    ckpt = tmp_path / "ck"
    argv, repo, env = _train_cmd(
        ckpt, "--resilience.fault=delay:step=3,ms=3000")
    proc = subprocess.Popen(argv, cwd=repo, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    hb = ckpt / "obs" / "heartbeat_r00000.jsonl"
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if hb.exists() and hb.read_text().count("\n") >= 2:
            break
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 143, out
    payload = _assert_blackbox(ckpt, "PreemptedError")
    assert any(e["kind"] == "preempt_signal" for e in payload["events"])


def test_dump_on_unhandled_exception_in_process(tmp_path):
    """An arbitrary crash inside the epoch loop still leaves the black
    box, stamped with the exception — fit()'s finally owns the dump, not
    any particular error type."""
    from tpu_dp.train.hooks import StepHook
    from tpu_dp.train.trainer import Trainer
    from tpu_dp.config import Config

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 32
    c.data.synthetic_test_size = 16
    c.data.batch_size = 8
    c.train.epochs = 1
    c.train.log_every = 100
    c.train.eval_at_end = False
    c.train.ckpt_dir = str(tmp_path / "ck")
    tr = Trainer(c)

    class Bomb(StepHook):
        def on_step_end(self, ev):
            if self.tr._host_step >= 2:
                raise RuntimeError("simulated data-loader corruption")

    tr._hooks.insert(0, Bomb(tr))
    with pytest.raises(RuntimeError, match="corruption"):
        tr.fit()
    dump = flightrec.read_dump(
        tmp_path / "ck" / "obs" / "flightrec_r00000.json"
    )
    assert "RuntimeError" in dump["reason"]
    assert "corruption" in dump["reason"]
    assert dump["events"][-1]["kind"] == "exit"


def test_dump_on_clean_exit_and_disable_knob(tmp_path):
    """A clean run leaves a black box too (reason "clean") — obsctl's
    timeline needs the completion evidence; flightrec_capacity=0 turns
    the whole layer off."""
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 32
    c.data.synthetic_test_size = 16
    c.data.batch_size = 8
    c.train.epochs = 1
    c.train.log_every = 100
    c.train.eval_at_end = False
    c.train.ckpt_dir = str(tmp_path / "ck")
    Trainer(c).fit()
    dump = flightrec.read_dump(
        tmp_path / "ck" / "obs" / "flightrec_r00000.json"
    )
    assert dump["reason"] == "clean"
    assert {"epoch_start", "step", "exit"} <= {e["kind"]
                                              for e in dump["events"]}

    flightrec.recorder.reset()
    c2 = Config()
    c2.data.dataset = "synthetic"
    c2.data.synthetic_train_size = 32
    c2.data.synthetic_test_size = 16
    c2.data.batch_size = 8
    c2.train.epochs = 1
    c2.train.log_every = 100
    c2.train.eval_at_end = False
    c2.train.ckpt_dir = str(tmp_path / "ck2")
    c2.obs.flightrec_capacity = 0
    tr2 = Trainer(c2)
    assert tr2.flightrec is None
    tr2.fit()
    assert not list((tmp_path / "ck2").rglob("flightrec_r*.json"))
    # Disabled means DISABLED: the subsystems' module-level record()
    # calls were no-ops, not silent in-memory accumulation.
    assert flightrec.recorder.total_recorded == 0
    assert len(flightrec.recorder) == 0
