"""dplint (`tpu_dp.analysis`) — the static SPMD-correctness analyzer.

Three layers of coverage:

1. Adversarial fixtures (`tests/fixtures/dplint/`): one known-bad module
   per rule, DP101–DP104 and DP201–DP204. Each fixture marks the line its
   finding must be attributed to with an ``# EXPECT: <RULE>`` comment; the
   test drives the real CLI (`tpu_dp.analysis.cli.main`) and asserts the
   exit code, the rule id, the file, and the line.
2. The shipped tree is clean: `python -m tpu_dp.analysis tpu_dp/` exits 0
   (every legitimate gate carries an audited allow-pragma, every genuine
   finding was fixed).
3. The gradient-sync regression: the jaxpr pass proves the real
   `make_local_step` program reduces every parameter leaf's gradient over
   the data axis exactly once per optimizer update, for accum_steps 1 and
   >1 (guards against silent double-averaging under gradient
   accumulation).

Fast lane: ``pytest -m analysis``.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

from tpu_dp.analysis import astlint, lint_source
from tpu_dp.analysis.cli import main as dplint_main
from tpu_dp.analysis.report import RULES

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "dplint")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(DP\d{3})")

FIXTURE_FILES = sorted(
    f for f in os.listdir(FIXTURES) if f.endswith(".py")
)


def _expected_findings(path: str) -> list[tuple[str, int]]:
    """(rule, line) pairs a fixture's `# EXPECT: DPxxx` comments declare."""
    out = []
    with open(path, encoding="utf-8") as f:
        for lineno, text in enumerate(f, start=1):
            for m in _EXPECT_RE.finditer(text):
                out.append((m.group(1), lineno))
    return out


def _run_cli(capsys, argv: list[str]) -> tuple[int, dict]:
    rc = dplint_main(argv + ["--json"])
    payload = json.loads(capsys.readouterr().out)
    return rc, payload


# -- 1. every adversarial fixture fires its rule at its line -------------

@pytest.mark.parametrize("fixture", FIXTURE_FILES)
def test_fixture_fires_expected_rule(fixture, capsys):
    path = os.path.join(FIXTURES, fixture)
    expected = _expected_findings(path)
    assert expected, f"{fixture} declares no # EXPECT: comments"

    rc, payload = _run_cli(capsys, [path])
    assert rc == 1, f"{fixture}: expected exit 1, got {rc}"
    got = {(f["rule"], f["line"]) for f in payload["findings"]}
    for rule, line in expected:
        assert (rule, line) in got, (
            f"{fixture}: expected {rule} at line {line}, findings: {got}"
        )
    for f in payload["findings"]:
        assert f["path"] == path


def test_all_rules_covered_by_fixtures():
    """Every documented rule has at least one adversarial fixture.

    Level-4 host-protocol fixtures live in the `host/` subdirectory
    (driven by `tests/test_hostproto.py` through the `host` subcommand)
    and Level-5 concurrency fixtures in `conc/` (driven by
    `tests/test_concurrency.py` through the `conc` subcommand), not the
    device-program CLI this file exercises — but all count toward the
    same one-fixture-per-rule contract.
    """
    covered = set()
    paths = [os.path.join(FIXTURES, f) for f in FIXTURE_FILES]
    for sub in ("host", "conc"):
        sub_dir = os.path.join(FIXTURES, sub)
        paths += [
            os.path.join(sub_dir, f) for f in sorted(os.listdir(sub_dir))
            if f.endswith(".py")
        ]
    for path in paths:
        for rule, _ in _expected_findings(path):
            covered.add(rule)
    assert covered == set(RULES), (
        f"rules without a fixture: {set(RULES) - covered}"
    )


def test_every_rule_has_a_pragma_twin():
    """No rule ships untested in either direction: every DP1xx–DP5xx
    rule in RULES has at least one firing fixture (asserted above) and
    one pragma'd non-firing twin somewhere in the fixture tree — inline
    beside the firing case for the AST levels, under `allowed/` for the
    traced jaxpr/HLO levels (whose silence
    `test_pragma_twin_lints_clean` enforces), under `host/` and `conc/`
    for Levels 4 and 5."""
    allow_re = re.compile(r"#\s*dplint:\s*allow\(\s*(DP\d{3})")
    twinned: set[str] = set()
    for root, _dirs, files in os.walk(FIXTURES):
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            text = open(os.path.join(root, f), encoding="utf-8").read()
            twinned.update(m.group(1) for m in allow_re.finditer(text))
    assert twinned >= set(RULES), (
        f"rules without a pragma'd twin: {set(RULES) - twinned}"
    )


ALLOWED_DIR = os.path.join(FIXTURES, "allowed")
ALLOWED_FILES = sorted(
    f for f in os.listdir(ALLOWED_DIR) if f.endswith(".py")
)


@pytest.mark.parametrize("twin", ALLOWED_FILES)
def test_pragma_twin_lints_clean(twin, capsys):
    """The non-firing direction for the traced levels: the same bug
    shape as the sibling firing fixture, audited with a pragma on the
    hook program's `def` line (where the jaxpr/HLO passes attribute
    their findings) — the full CLI must exit 0."""
    path = os.path.join(ALLOWED_DIR, twin)
    rc, payload = _run_cli(capsys, [path, "--fingerprint-out", "none"])
    assert rc == 0, (
        f"{twin}: expected exit 0, got {rc}: {payload['findings']}"
    )
    assert payload["findings"] == []


# -- 2. the shipped tree is clean ----------------------------------------

def test_shipped_tree_is_clean_ast():
    """AST rules + donation check: zero unsuppressed findings in tpu_dp/."""
    rc = dplint_main([os.path.join(REPO, "tpu_dp"), "--no-jaxpr"])
    assert rc == 0


def test_shipped_tree_is_clean_full(capsys):
    """The full two-level run (`python -m tpu_dp.analysis tpu_dp/`) exits 0:
    AST rules, donation check, and the jaxpr gradient-sync pass over the
    real step for accum_steps ∈ {1, 2}."""
    rc, payload = _run_cli(capsys, [os.path.join(REPO, "tpu_dp")])
    assert payload["findings"] == []
    assert rc == 0


def test_cli_launcher_runs_from_checkout():
    """tools/dplint.py works without installing the package."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dplint.py"),
         "--list-rules"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    for rule in RULES:
        assert rule in proc.stdout


def test_changed_mode_lints_only_the_diff(tmp_path):
    """`tools/dplint.py host --changed` resolves the git repo of its cwd,
    diffs against the merge-base, and lints only what moved: a clean tree
    exits 0 with a no-op note, and a freshly added violation exits 1."""
    def git(*args):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=tmp_path, check=True, capture_output=True, text=True,
        )

    git("init", "-q")
    (tmp_path / "README").write_text("scratch repo\n")
    git("add", "README")
    git("commit", "-qm", "seed")

    launcher = os.path.join(REPO, "tools", "dplint.py")
    proc = subprocess.run(
        [sys.executable, launcher, "host", "--changed"],
        cwd=tmp_path, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no python files differ" in proc.stdout

    (tmp_path / "bad.py").write_text(
        "from pathlib import Path\n"
        "\n"
        "def persist(rank, blob):\n"
        "    Path('ck.bin').write_text(blob)\n"
    )
    proc = subprocess.run(
        [sys.executable, launcher, "host", "--changed", "--json"],
        cwd=tmp_path, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert "DP401" in rules, rules


# -- 3. gradient-sync regression: exactly one reduction per leaf ---------

@pytest.mark.parametrize("accum_steps", [1, 4])
def test_exactly_one_reduction_per_param_leaf(accum_steps):
    """The shipped per-shard step reduces every parameter gradient over the
    data axis exactly once per optimizer update — including under gradient
    accumulation, where the single reduction must sit after the microbatch
    scan (one pmean per update, never one per microbatch)."""
    from tpu_dp.analysis import gradsync

    findings, report = gradsync.verify_repo_step(accum_steps=accum_steps)
    assert findings == []
    assert report, "no parameter leaves found in the step outputs"
    bad = {ks: n for ks, n in report.items() if n != 1}
    assert not bad, (
        f"accum_steps={accum_steps}: leaves without exactly one data-axis "
        f"reduction: {bad}"
    )


def test_sync_bn_model_verifies_without_false_double_reduction():
    """Sync-BN models do in-forward data-axis collectives whose AD
    transposes sit on every gradient's backward path — legitimately more
    than one reduction per leaf. verify_repo_step must drop to the
    at-least-once half of the contract (no DP202 noise) while still
    catching DP201."""
    from tpu_dp.analysis import gradsync
    from tpu_dp.parallel.dist import DATA_AXIS

    findings, report = gradsync.verify_repo_step(
        model_name="resnet18", num_filters=8, axis_name=DATA_AXIS
    )
    assert findings == []
    assert report and all(n >= 1 for n in report.values())


def test_accum_report_has_same_leaves_as_plain():
    """Accumulation changes the schedule, not the parameter tree: both
    variants must verify the identical set of gradient leaves."""
    from tpu_dp.analysis import gradsync

    _, plain = gradsync.verify_repo_step(accum_steps=1)
    _, accum = gradsync.verify_repo_step(accum_steps=3)
    assert set(plain) == set(accum)


# -- reviewer regressions -------------------------------------------------

def test_nested_rank_gates_report_collective_once():
    """A collective under two nested rank gates belongs to the innermost
    gate: one finding, clearable by one pragma."""
    src = (
        "import jax\n"
        "from tpu_dp.parallel import collectives\n"
        "def f(rank, m):\n"
        "    if jax.process_index() == 0:\n"
        "        if rank == 0:\n"
        "            collectives.psum(m)\n"
    )
    findings = lint_source("x.py", src)
    assert [(f.rule, f.line) for f in findings] == [("DP101", 6)]
    # The pragma on the inner gate line clears the file.
    suppressed = src.replace(
        "if rank == 0:", "if rank == 0:  # dplint: allow(DP101)"
    )
    assert lint_source("x.py", suppressed) == []


def test_donation_multiline_call_argument_is_not_a_read():
    """The donated argument's own Load inside a line-wrapped call is not a
    read-after-donation; a genuine later read still is."""
    from tpu_dp.analysis import donation

    ok = (
        "from tpu_dp.train.step import make_train_step\n"
        "def loop(model, opt, mesh, sched, state, batch):\n"
        "    train_step = make_train_step(model, opt, mesh, sched)\n"
        "    state, metrics = train_step(\n"
        "        state, batch)\n"
        "    return state, metrics\n"
    )
    assert donation.check_source("x.py", ok) == []

    bad = (
        "from tpu_dp.train.step import make_train_step\n"
        "def loop(model, opt, mesh, sched, state, batch):\n"
        "    train_step = make_train_step(model, opt, mesh, sched)\n"
        "    new_state, metrics = train_step(\n"
        "        state, batch)\n"
        "    return state.params\n"
    )
    findings = donation.check_source("x.py", bad)
    assert [(f.rule, f.line) for f in findings] == [("DP204", 6)]


# -- pragma handling ------------------------------------------------------

def test_pragma_suppresses_only_named_rule():
    src = (
        "import jax\n"
        "def f(g):\n"
        "    return jax.lax.psum(g, 'data')  # dplint: allow(DP103)\n"
        "def g(g):\n"
        "    return jax.lax.psum(g, 'data')\n"
    )
    findings = lint_source("x.py", src)
    assert [(f.rule, f.line) for f in findings] == [("DP103", 5)]


def test_pragma_on_gate_line_covers_block():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    if jax.process_index() == 0:  # dplint: allow(DP101)\n"
        "        print('host-only IO', x)\n"
    )
    assert lint_source("x.py", src) == []


def test_pragma_inside_string_does_not_suppress():
    src = (
        "import jax\n"
        "MSG = '# dplint: allow(DP103)'\n"
        "def f(g):\n"
        "    return jax.lax.psum(g, 'data')\n"
    )
    findings = lint_source("x.py", src)
    assert [f.rule for f in findings] == ["DP103"]


def test_iter_py_files_skips_pycache(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\n")
    cache = tmp_path / "__pycache__"
    cache.mkdir()
    (cache / "a.cpython-310.py").write_text("x = 1\n")
    files = astlint.iter_py_files([str(tmp_path)])
    assert files == [str(tmp_path / "a.py")]


# -- CLI exit codes + partial findings on internal error ------------------

_RAW_PSUM = (
    "import jax\n"
    "def f(g):\n"
    "    return jax.lax.psum(g, 'data')\n"
)


def test_cli_exit_0_on_clean_file(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc, payload = _run_cli(capsys, [str(tmp_path)])
    assert rc == 0 and payload["findings"] == []


def test_cli_exit_1_on_findings(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(_RAW_PSUM)
    rc, payload = _run_cli(capsys, [str(tmp_path)])
    assert rc == 1
    assert [f["rule"] for f in payload["findings"]] == ["DP103"]


def test_cli_exit_2_renders_partial_findings(tmp_path, capsys):
    """An internal error (exit 2) must not discard the findings already
    collected: they render to stdout (marked partial, still valid JSON)
    while the traceback goes to stderr."""
    (tmp_path / "bad.py").write_text(_RAW_PSUM)
    # A Level-2 hook whose module import explodes: the AST findings above
    # were already collected when the crash happens.
    (tmp_path / "boom.py").write_text(
        "raise RuntimeError('fixture import explodes')\n"
        "def DPLINT_LOCAL_STEP():\n"
        "    pass\n"
    )
    rc = dplint_main([str(tmp_path), "--json"])
    captured = capsys.readouterr()
    assert rc == 2
    payload = json.loads(captured.out)  # stdout stays machine-parseable
    assert payload["partial"] is True
    assert "RuntimeError" in payload["internal_error"]
    assert [f["rule"] for f in payload["findings"]] == ["DP103"]
    assert "Traceback" in captured.err  # the traceback went to stderr


@pytest.mark.parametrize("spec", ["0", "abc", "-3"])
def test_cli_bad_accum_steps_is_usage_error(spec, capsys):
    """`--accum-steps` garbage is a clean exit-2 usage diagnostic on
    stderr, not a traceback dressed as an internal error."""
    rc = dplint_main(["--accum-steps", spec, os.path.join(FIXTURES,
                                                          "__nope__")])
    captured = capsys.readouterr()
    assert rc == 2
    assert "bad --accum-steps" in captured.err
    assert "Traceback" not in captured.err


def test_parse_accum_accepts_lists():
    from tpu_dp.analysis.cli import _parse_accum

    assert _parse_accum("1,2, 4") == [1, 2, 4]
    assert _parse_accum("") == [1]
    with pytest.raises(ValueError):
        _parse_accum("0")


# -- baseline suppression (stable fingerprints) ---------------------------

def test_baseline_suppresses_preexisting_findings(tmp_path, capsys):
    """CI adoption path: --write-baseline records today's findings by
    rule+path+symbol fingerprint; --baseline then exits 0 on them — and
    keeps exiting 0 after unrelated edits shift every line number."""
    target = tmp_path / "legacy.py"
    target.write_text(_RAW_PSUM)
    rc, payload = _run_cli(capsys, [str(target)])
    assert rc == 1
    fp = payload["findings"][0]["fingerprint"]
    assert fp.startswith("DP103:") and fp.endswith(":f")
    assert not any(ch.isdigit() for ch in fp.rsplit(":", 1)[-1])

    baseline = tmp_path / "baseline.json"
    rc = dplint_main([str(target), "--write-baseline", str(baseline)])
    capsys.readouterr()
    assert rc == 0
    assert json.loads(baseline.read_text())["suppress"]

    rc, payload = _run_cli(
        capsys, [str(target), "--baseline", str(baseline)]
    )
    assert rc == 0 and payload["findings"] == []

    # Unrelated edit: the finding moves two lines down; fingerprint holds.
    target.write_text("# moved\n# down\n" + _RAW_PSUM)
    rc, payload = _run_cli(
        capsys, [str(target), "--baseline", str(baseline)]
    )
    assert rc == 0 and payload["findings"] == []

    # A NEW rule violation in the same file is not masked by the baseline.
    target.write_text(_RAW_PSUM + "def g(h):\n"
                      "    return jax.lax.psum(h, 'model')\n")
    rc, payload = _run_cli(
        capsys, [str(target), "--baseline", str(baseline)]
    )
    assert rc == 1
    assert {f["symbol"] for f in payload["findings"]} == {"g"}


def test_cli_bad_baseline_is_usage_error(tmp_path, capsys):
    bad = tmp_path / "b.json"
    bad.write_text('{"wrong": true}')
    rc = dplint_main([str(tmp_path), "--baseline", str(bad)])
    captured = capsys.readouterr()
    assert rc == 2
    assert "bad --baseline" in captured.err


def test_no_jaxpr_skips_step_hook_module_import(tmp_path, capsys):
    """--no-jaxpr must not execute DPLINT_LOCAL_STEP-only fixture modules:
    a broken/expensive fixture import cannot crash a pass that was
    explicitly disabled."""
    (tmp_path / "boom.py").write_text(
        "raise RuntimeError('must not import under --no-jaxpr')\n"
        "def DPLINT_LOCAL_STEP():\n"
        "    pass\n"
    )
    rc, payload = _run_cli(capsys, [str(tmp_path), "--no-jaxpr"])
    assert rc == 0 and payload["findings"] == []


def test_write_baseline_refresh_in_place_keeps_entries(tmp_path, capsys):
    """`--baseline ci.json --write-baseline ci.json` (the natural refresh)
    must re-record still-present findings, not empty the file."""
    target = tmp_path / "legacy.py"
    target.write_text(_RAW_PSUM)
    baseline = tmp_path / "ci.json"
    assert dplint_main([str(target), "--write-baseline", str(baseline)]) == 0
    capsys.readouterr()
    assert len(json.loads(baseline.read_text())["suppress"]) == 1

    rc = dplint_main([str(target), "--baseline", str(baseline),
                      "--write-baseline", str(baseline)])
    capsys.readouterr()
    assert rc == 0
    assert len(json.loads(baseline.read_text())["suppress"]) == 1


def test_write_baseline_refuses_partial_findings(tmp_path, capsys):
    """An internal error mid-run must not persist a truncated baseline."""
    (tmp_path / "bad.py").write_text(_RAW_PSUM)
    (tmp_path / "boom.py").write_text(
        "raise RuntimeError('explodes')\n"
        "def DPLINT_LOCAL_STEP():\n"
        "    pass\n"
    )
    baseline = tmp_path / "ci.json"
    rc = dplint_main([str(tmp_path), "--write-baseline", str(baseline)])
    captured = capsys.readouterr()
    assert rc == 2
    assert not baseline.exists()
    assert "refusing to write baseline" in captured.err


def test_fingerprint_distinguishes_same_named_files_outside_repo(tmp_path):
    from tpu_dp.analysis.report import Finding, fingerprint

    a = Finding("DP103", str(tmp_path / "a" / "steps.py"), 3, "m", symbol="f")
    b = Finding("DP103", str(tmp_path / "b" / "steps.py"), 3, "m", symbol="f")
    assert fingerprint(a) != fingerprint(b)
