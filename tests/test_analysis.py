"""dplint (`tpu_dp.analysis`) — the static SPMD-correctness analyzer.

Three layers of coverage:

1. Adversarial fixtures (`tests/fixtures/dplint/`): one known-bad module
   per rule, DP101–DP104 and DP201–DP204. Each fixture marks the line its
   finding must be attributed to with an ``# EXPECT: <RULE>`` comment; the
   test drives the real CLI (`tpu_dp.analysis.cli.main`) and asserts the
   exit code, the rule id, the file, and the line.
2. The shipped tree is clean: `python -m tpu_dp.analysis tpu_dp/` exits 0
   (every legitimate gate carries an audited allow-pragma, every genuine
   finding was fixed).
3. The gradient-sync regression: the jaxpr pass proves the real
   `make_local_step` program reduces every parameter leaf's gradient over
   the data axis exactly once per optimizer update, for accum_steps 1 and
   >1 (guards against silent double-averaging under gradient
   accumulation).

Fast lane: ``pytest -m analysis``.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

from tpu_dp.analysis import astlint, lint_source
from tpu_dp.analysis.cli import main as dplint_main
from tpu_dp.analysis.report import RULES

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "dplint")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(DP\d{3})")

FIXTURE_FILES = sorted(
    f for f in os.listdir(FIXTURES) if f.endswith(".py")
)


def _expected_findings(path: str) -> list[tuple[str, int]]:
    """(rule, line) pairs a fixture's `# EXPECT: DPxxx` comments declare."""
    out = []
    with open(path, encoding="utf-8") as f:
        for lineno, text in enumerate(f, start=1):
            for m in _EXPECT_RE.finditer(text):
                out.append((m.group(1), lineno))
    return out


def _run_cli(capsys, argv: list[str]) -> tuple[int, dict]:
    rc = dplint_main(argv + ["--json"])
    payload = json.loads(capsys.readouterr().out)
    return rc, payload


# -- 1. every adversarial fixture fires its rule at its line -------------

@pytest.mark.parametrize("fixture", FIXTURE_FILES)
def test_fixture_fires_expected_rule(fixture, capsys):
    path = os.path.join(FIXTURES, fixture)
    expected = _expected_findings(path)
    assert expected, f"{fixture} declares no # EXPECT: comments"

    rc, payload = _run_cli(capsys, [path])
    assert rc == 1, f"{fixture}: expected exit 1, got {rc}"
    got = {(f["rule"], f["line"]) for f in payload["findings"]}
    for rule, line in expected:
        assert (rule, line) in got, (
            f"{fixture}: expected {rule} at line {line}, findings: {got}"
        )
    for f in payload["findings"]:
        assert f["path"] == path


def test_all_rules_covered_by_fixtures():
    """Every documented rule has at least one adversarial fixture."""
    covered = set()
    for fixture in FIXTURE_FILES:
        for rule, _ in _expected_findings(os.path.join(FIXTURES, fixture)):
            covered.add(rule)
    assert covered == set(RULES), (
        f"rules without a fixture: {set(RULES) - covered}"
    )


# -- 2. the shipped tree is clean ----------------------------------------

def test_shipped_tree_is_clean_ast():
    """AST rules + donation check: zero unsuppressed findings in tpu_dp/."""
    rc = dplint_main([os.path.join(REPO, "tpu_dp"), "--no-jaxpr"])
    assert rc == 0


def test_shipped_tree_is_clean_full(capsys):
    """The full two-level run (`python -m tpu_dp.analysis tpu_dp/`) exits 0:
    AST rules, donation check, and the jaxpr gradient-sync pass over the
    real step for accum_steps ∈ {1, 2}."""
    rc, payload = _run_cli(capsys, [os.path.join(REPO, "tpu_dp")])
    assert payload["findings"] == []
    assert rc == 0


def test_cli_launcher_runs_from_checkout():
    """tools/dplint.py works without installing the package."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dplint.py"),
         "--list-rules"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    for rule in RULES:
        assert rule in proc.stdout


# -- 3. gradient-sync regression: exactly one reduction per leaf ---------

@pytest.mark.parametrize("accum_steps", [1, 4])
def test_exactly_one_reduction_per_param_leaf(accum_steps):
    """The shipped per-shard step reduces every parameter gradient over the
    data axis exactly once per optimizer update — including under gradient
    accumulation, where the single reduction must sit after the microbatch
    scan (one pmean per update, never one per microbatch)."""
    from tpu_dp.analysis import gradsync

    findings, report = gradsync.verify_repo_step(accum_steps=accum_steps)
    assert findings == []
    assert report, "no parameter leaves found in the step outputs"
    bad = {ks: n for ks, n in report.items() if n != 1}
    assert not bad, (
        f"accum_steps={accum_steps}: leaves without exactly one data-axis "
        f"reduction: {bad}"
    )


def test_sync_bn_model_verifies_without_false_double_reduction():
    """Sync-BN models do in-forward data-axis collectives whose AD
    transposes sit on every gradient's backward path — legitimately more
    than one reduction per leaf. verify_repo_step must drop to the
    at-least-once half of the contract (no DP202 noise) while still
    catching DP201."""
    from tpu_dp.analysis import gradsync
    from tpu_dp.parallel.dist import DATA_AXIS

    findings, report = gradsync.verify_repo_step(
        model_name="resnet18", num_filters=8, axis_name=DATA_AXIS
    )
    assert findings == []
    assert report and all(n >= 1 for n in report.values())


def test_accum_report_has_same_leaves_as_plain():
    """Accumulation changes the schedule, not the parameter tree: both
    variants must verify the identical set of gradient leaves."""
    from tpu_dp.analysis import gradsync

    _, plain = gradsync.verify_repo_step(accum_steps=1)
    _, accum = gradsync.verify_repo_step(accum_steps=3)
    assert set(plain) == set(accum)


# -- reviewer regressions -------------------------------------------------

def test_nested_rank_gates_report_collective_once():
    """A collective under two nested rank gates belongs to the innermost
    gate: one finding, clearable by one pragma."""
    src = (
        "import jax\n"
        "from tpu_dp.parallel import collectives\n"
        "def f(rank, m):\n"
        "    if jax.process_index() == 0:\n"
        "        if rank == 0:\n"
        "            collectives.psum(m)\n"
    )
    findings = lint_source("x.py", src)
    assert [(f.rule, f.line) for f in findings] == [("DP101", 6)]
    # The pragma on the inner gate line clears the file.
    suppressed = src.replace(
        "if rank == 0:", "if rank == 0:  # dplint: allow(DP101)"
    )
    assert lint_source("x.py", suppressed) == []


def test_donation_multiline_call_argument_is_not_a_read():
    """The donated argument's own Load inside a line-wrapped call is not a
    read-after-donation; a genuine later read still is."""
    from tpu_dp.analysis import donation

    ok = (
        "from tpu_dp.train.step import make_train_step\n"
        "def loop(model, opt, mesh, sched, state, batch):\n"
        "    train_step = make_train_step(model, opt, mesh, sched)\n"
        "    state, metrics = train_step(\n"
        "        state, batch)\n"
        "    return state, metrics\n"
    )
    assert donation.check_source("x.py", ok) == []

    bad = (
        "from tpu_dp.train.step import make_train_step\n"
        "def loop(model, opt, mesh, sched, state, batch):\n"
        "    train_step = make_train_step(model, opt, mesh, sched)\n"
        "    new_state, metrics = train_step(\n"
        "        state, batch)\n"
        "    return state.params\n"
    )
    findings = donation.check_source("x.py", bad)
    assert [(f.rule, f.line) for f in findings] == [("DP204", 6)]


# -- pragma handling ------------------------------------------------------

def test_pragma_suppresses_only_named_rule():
    src = (
        "import jax\n"
        "def f(g):\n"
        "    return jax.lax.psum(g, 'data')  # dplint: allow(DP103)\n"
        "def g(g):\n"
        "    return jax.lax.psum(g, 'data')\n"
    )
    findings = lint_source("x.py", src)
    assert [(f.rule, f.line) for f in findings] == [("DP103", 5)]


def test_pragma_on_gate_line_covers_block():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    if jax.process_index() == 0:  # dplint: allow(DP101)\n"
        "        print('host-only IO', x)\n"
    )
    assert lint_source("x.py", src) == []


def test_pragma_inside_string_does_not_suppress():
    src = (
        "import jax\n"
        "MSG = '# dplint: allow(DP103)'\n"
        "def f(g):\n"
        "    return jax.lax.psum(g, 'data')\n"
    )
    findings = lint_source("x.py", src)
    assert [f.rule for f in findings] == ["DP103"]


def test_iter_py_files_skips_pycache(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\n")
    cache = tmp_path / "__pycache__"
    cache.mkdir()
    (cache / "a.cpython-310.py").write_text("x = 1\n")
    files = astlint.iter_py_files([str(tmp_path)])
    assert files == [str(tmp_path / "a.py")]
