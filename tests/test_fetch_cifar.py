"""tools/fetch_cifar.py — everything testable without egress.

The download itself needs the network this box doesn't have; what these
tests pin down is the rest of the contract: the extracted layout is exactly
what `tpu_dp.data.cifar.load_dataset` reads (end-to-end through the
production reader), checksum failures are fatal and leave no partial file,
extraction is allowlisted (a hostile archive can't escape the root), and
the egress gate answers quickly instead of hanging.
"""

import hashlib
import io
import pickle
import tarfile

import numpy as np
import pytest

from tools import fetch_cifar


def _fake_cifar10_tar(tmp_path, n_per_batch=4):
    """A miniature cifar-10-python.tar.gz in the canonical layout."""
    rng = np.random.default_rng(0)
    batches = {}
    for fname in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        data = rng.integers(0, 256, size=(n_per_batch, 3072), dtype=np.uint8)
        labels = rng.integers(0, 10, size=n_per_batch).tolist()
        batches[fname] = {b"data": data, b"labels": labels}
    tar_path = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        for fname, payload in batches.items():
            blob = pickle.dumps(payload)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{fname}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return tar_path, batches


def test_extract_then_production_reader_roundtrip(tmp_path):
    tar_path, batches = _fake_cifar10_tar(tmp_path)
    root = tmp_path / "data"
    spec = fetch_cifar.SPECS["cifar10"]
    out = fetch_cifar.extract(tar_path, root, spec["dirname"], spec["files"])
    assert len(out) == 6

    from tpu_dp.data.cifar import load_dataset

    ds = load_dataset("cifar10", root, train=True, allow_synthetic=False)
    assert not ds.synthetic and len(ds) == 20 and ds.num_classes == 10
    # Pixel-exact CHW->NHWC: first example of data_batch_1.
    flat = batches["data_batch_1"][b"data"][0]
    np.testing.assert_array_equal(
        ds.images[0], flat.reshape(3, 32, 32).transpose(1, 2, 0)
    )
    assert ds.labels[0] == batches["data_batch_1"][b"labels"][0]

    test_ds = load_dataset("cifar10", root, train=False, allow_synthetic=False)
    assert not test_ds.synthetic and len(test_ds) == 4


def test_extract_missing_member_raises(tmp_path):
    tar_path, _ = _fake_cifar10_tar(tmp_path)
    with pytest.raises(RuntimeError, match="missing member"):
        fetch_cifar.extract(tar_path, tmp_path / "data",
                            "cifar-10-batches-py", ["data_batch_99"])


def test_extract_ignores_traversal_members(tmp_path):
    # A member named ../evil must be unreachable: extraction looks up only
    # the allowlisted <dirname>/<fname> names.
    tar_path = tmp_path / "hostile.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        blob = b"pwned"
        info = tarfile.TarInfo("../evil")
        info.size = len(blob)
        tf.addfile(info, io.BytesIO(blob))
        ok = pickle.dumps({b"data": np.zeros((1, 3072), np.uint8),
                           b"labels": [0]})
        info2 = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
        info2.size = len(ok)
        tf.addfile(info2, io.BytesIO(ok))
    root = tmp_path / "data"
    fetch_cifar.extract(tar_path, root, "cifar-10-batches-py",
                        ["data_batch_1"])
    assert (root / "cifar-10-batches-py" / "data_batch_1").exists()
    assert not (tmp_path / "evil").exists() and not (root / "evil").exists()


def test_download_verifies_md5_via_file_url(tmp_path):
    src = tmp_path / "payload.bin"
    src.write_bytes(b"cifar bytes")
    url = src.as_uri()
    good = hashlib.md5(b"cifar bytes").hexdigest()
    dest = tmp_path / "out.bin"
    fetch_cifar.download(url, dest, good)
    assert dest.read_bytes() == b"cifar bytes"

    bad_dest = tmp_path / "out2.bin"
    with pytest.raises(RuntimeError, match="md5 mismatch"):
        fetch_cifar.download(url, bad_dest, "0" * 32)
    assert not bad_dest.exists()  # no truncated/poisoned file left behind


def test_egress_probe_fails_fast_offline():
    import time

    t0 = time.monotonic()
    # Port 9 (discard) on loopback: nothing listens, refusal is immediate;
    # the probe must answer False quickly, never hang.
    assert fetch_cifar.egress_available("127.0.0.1", 9, timeout_s=0.5) is False
    assert time.monotonic() - t0 < 5


def test_verify_layout_reports_missing(tmp_path, capsys):
    assert fetch_cifar.verify_layout(tmp_path, "cifar10") is False
    out = capsys.readouterr().out
    assert "FAIL" in out
