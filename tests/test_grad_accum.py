"""Gradient accumulation: accum_steps microbatches ≡ one big batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.config import Config
from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.data.pipeline import DataPipeline
from tpu_dp.models import Net
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step
from tpu_dp.train.trainer import Trainer


def _copy(state):
    return jax.tree_util.tree_map(jnp.array, state)


def test_accum_equivalent_to_big_batch(mesh8):
    model, opt = Net(), SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    ds = make_synthetic(32, 10, seed=0, name="ga")
    imgs, labels = normalize(ds.images), ds.labels

    big = make_train_step(model, opt, mesh8, constant_lr(0.05))
    acc = make_train_step(model, opt, mesh8, constant_lr(0.05), accum_steps=4)

    s_big, m_big = big(_copy(state), {"image": imgs, "label": labels})
    s_acc, m_acc = acc(
        _copy(state),
        {
            "image": imgs.reshape(4, 8, 32, 32, 3),
            "label": labels.reshape(4, 8),
        },
    )
    # Equal microbatch sizes ⇒ mean-of-means == global mean: identical
    # update and identical metrics.
    assert float(m_acc["loss"]) == pytest.approx(float(m_big["loss"]), rel=1e-5)
    assert int(m_acc["correct"]) == int(m_big["correct"])
    assert int(m_acc["count"]) == int(m_big["count"]) == 32
    for a, b in zip(
        jax.tree_util.tree_leaves(s_acc.params),
        jax.tree_util.tree_leaves(s_big.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_pipeline_accum_grouping(mesh8):
    ds = make_synthetic(128, 10, seed=1, name="ga")
    pipe = DataPipeline(ds, batch_size=16, mesh=mesh8, accum_steps=2,
                        shuffle=False, prefetch=0)
    assert len(pipe) == 4  # 128 / (16·2)
    batches = list(pipe)
    assert len(batches) == 4
    for b in batches:
        assert b["image"].shape == (2, 16, 32, 32, 3)
        assert b["label"].shape == (2, 16)


def test_multi_step_composes_with_accum(mesh8):
    """Scan-of-scan: `make_multi_step(accum_steps=a)` ≡ sequential
    `make_train_step(accum_steps=a)` calls (VERDICT r4 next-steps #4)."""
    from tpu_dp.train import make_multi_step

    model, opt = Net(), SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    ds = make_synthetic(64, 10, seed=0, name="ga")
    imgs, labels = normalize(ds.images), ds.labels
    # 2 windowed steps × 2 microbatches × batch 16.
    pool = {
        "image": imgs.reshape(2, 2, 16, 32, 32, 3),
        "label": labels.reshape(2, 2, 16),
    }

    per_step = make_train_step(model, opt, mesh8, constant_lr(0.05),
                               accum_steps=2)
    s_ref = _copy(state)
    losses = []
    for j in range(2):
        s_ref, m = per_step(
            s_ref,
            {"image": pool["image"][j], "label": pool["label"][j]},
        )
        losses.append(float(m["loss"]))

    loop = make_multi_step(model, opt, mesh8, constant_lr(0.05),
                           num_steps=2, accum_steps=2)
    s_win, stacked = loop(_copy(state), pool)

    assert int(s_win.step) == int(s_ref.step) == 2
    np.testing.assert_allclose(
        np.asarray(stacked["loss"]), np.asarray(losses), rtol=1e-5
    )
    assert int(stacked["count"][0]) == 32  # accum × batch per update
    for a, b in zip(
        jax.tree_util.tree_leaves(s_win.params),
        jax.tree_util.tree_leaves(s_ref.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_trainer_with_accum(tmp_path):
    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 128
    c.data.synthetic_test_size = 32
    c.data.batch_size = 16
    c.data.prefetch = 1
    c.optim.grad_accum_steps = 2
    c.optim.lr = 0.05
    c.train.epochs = 2
    c.train.ckpt_dir = str(tmp_path / "ck")
    result = Trainer(c).fit()
    assert result["history"][1]["loss"] < result["history"][0]["loss"]
