"""Logit-level parity: torch `Net` weights imported into the Flax `Net`.

The strongest possible parity check against the reference's model spec
(`cifar_example.py:17-34`): an independently-constructed torch CNN with the
same topology, random weights, must produce (numerically) identical logits
through the Flax model after `import_net_state_dict` — proving the layout
mapping (OIHW↔HWIO, linear transpose, NCHW/NHWC flatten permutation, and
DDP's `module.` prefix handling) is exact.
"""

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from tpu_dp.compat import export_net_state_dict, import_net_state_dict
from tpu_dp.models import Net


def _torch_net():
    """Reference-topology CNN built with torch (spec: cifar_example.py:17-34)."""
    import torch.nn as tnn

    class TorchNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(3, 6, 5)
            self.conv2 = tnn.Conv2d(6, 16, 5)
            self.fc1 = tnn.Linear(400, 120)
            self.fc2 = tnn.Linear(120, 84)
            self.fc3 = tnn.Linear(84, 10)
            self.pool = tnn.MaxPool2d(2, 2)

        def forward(self, x):
            x = self.pool(torch.relu(self.conv1(x)))
            x = self.pool(torch.relu(self.conv2(x)))
            x = torch.flatten(x, 1)
            x = torch.relu(self.fc1(x))
            x = torch.relu(self.fc2(x))
            return self.fc3(x)

    return TorchNet()


def _logits_match(tnet, params, atol=1e-5):
    rng = np.random.default_rng(0)
    x_nchw = rng.normal(size=(8, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        expected = tnet(torch.tensor(x_nchw)).numpy()
    model = Net()
    got = np.asarray(
        model.apply({"params": params}, x_nchw.transpose(0, 2, 3, 1))
    )
    np.testing.assert_allclose(got, expected, atol=atol, rtol=1e-4)


def test_import_torch_weights_logit_parity():
    tnet = _torch_net()
    sd = {k: v.detach().numpy() for k, v in tnet.state_dict().items()}
    params = import_net_state_dict(sd)
    _logits_match(tnet, params)


def test_import_handles_ddp_module_prefix():
    tnet = _torch_net()
    sd = {
        f"module.{k}": v.detach().numpy() for k, v in tnet.state_dict().items()
    }
    params = import_net_state_dict(sd)
    _logits_match(tnet, params)


def test_export_roundtrip():
    model = Net()
    variables = model.init(
        jax.random.PRNGKey(3), np.zeros((1, 32, 32, 3), np.float32)
    )
    sd = export_net_state_dict(variables["params"])
    back = import_net_state_dict(sd)
    for a, b in zip(
        jax.tree_util.tree_leaves(back),
        jax.tree_util.tree_leaves(variables["params"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Exported weights drive a torch Net to the same logits too.
    tnet = _torch_net()
    tnet.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    _logits_match(tnet, variables["params"])
