"""tools/profile_breakdown.py — xplane parsing, no device required.

Builds a synthetic XSpace proto (one device plane, one 'XLA Ops' line, a
%while wrapper spanning two real ops with hlo_category/model_flops/
bytes_accessed stats) and checks the report: wrapper excluded from the
category totals, categories aggregated, per-op TFLOP/s computed, and all
four diagnostic exits (no xplane file, eventless device plane, missing
'XLA Ops' line, wrapper-only trace).
"""

import pytest

try:
    # Needs the pure-python protobuf runtime (the C++ backend rejects the
    # TF-generated module with TypeError, not ImportError) — the tool
    # re-execs itself with this env var; tests must skip without it.
    from tensorflow.tsl.profiler.protobuf import xplane_pb2 as tf_xplane
except Exception as e:  # noqa: BLE001 - any import failure means skip
    pytest.skip(f"TF xplane proto unavailable ({type(e).__name__})",
                allow_module_level=True)

from tools import profile_breakdown  # noqa: E402


def _stat_md(plane, sid, name):
    plane.stat_metadata[sid].id = sid
    plane.stat_metadata[sid].name = name
    return sid


def _build_xspace(tmp_path, wrapper_only=False, line_name="XLA Ops"):
    xs = tf_xplane.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    s_cat = _stat_md(plane, 1, "hlo_category")
    s_flops = _stat_md(plane, 2, "model_flops")
    s_bytes = _stat_md(plane, 3, "bytes_accessed")

    def event_md(eid, name, cat=None, flops=0, nbytes=0):
        md = plane.event_metadata[eid]
        md.id = eid
        md.name = name
        if cat is not None:
            st = md.stats.add()
            st.metadata_id = s_cat
            st.str_value = cat
        for sid, val in ((s_flops, flops), (s_bytes, nbytes)):
            if val:
                st = md.stats.add()
                st.metadata_id = sid
                st.int64_value = val
        return eid

    line = plane.lines.add()
    line.name = line_name
    # scan wrapper: 10 ms spanning everything — must not count as work
    event_md(10, "%while.1 = ...")
    e = line.events.add()
    e.metadata_id = 10
    e.offset_ps = 0
    e.duration_ps = int(10e9)
    if not wrapper_only:
        # a conv: 6 ms, 1.2e9 FLOPs
        event_md(11, "%convert_reduce_fusion.1 = ...",
                 cat="convolution fusion", flops=int(1.2e9), nbytes=int(3e6))
        e = line.events.add()
        e.metadata_id = 11
        e.offset_ps = 0
        e.duration_ps = int(6e9)
        # an elementwise fusion: 4 ms
        event_md(12, "%fusion.9 = ...", cat="loop fusion", flops=0,
                 nbytes=int(8e6))
        e = line.events.add()
        e.metadata_id = 12
        e.offset_ps = int(6e9)
        e.duration_ps = int(4e9)
    p = tmp_path / "t.xplane.pb"
    p.write_bytes(xs.SerializeToString())
    return p


def test_report_aggregates_and_excludes_wrapper(tmp_path, capsys):
    _build_xspace(tmp_path)
    profile_breakdown.report(str(tmp_path), top=5)
    out = capsys.readouterr().out
    # Window = the while span; busy = the two real ops; idle = 0.
    assert "window 10.0 ms" in out and "op-busy 10.0 ms" in out
    assert "convolution fusion" in out and "loop fusion" in out
    # 60/40 split between the categories.
    assert " 60.0%" in out and " 40.0%" in out
    # Per-op rate: 1.2e9 FLOPs / 6 ms = 0.2 TF/s.
    assert "%convert_reduce_fusion.1" in out
    # The wrapper never appears as an op row.
    assert "%while.1" not in out


def test_report_exits_on_empty_dir(tmp_path):
    with pytest.raises(SystemExit, match="no xplane.pb"):
        profile_breakdown.report(str(tmp_path), top=5)


def test_report_exits_when_only_wrapper_events(tmp_path):
    _build_xspace(tmp_path, wrapper_only=True)
    with pytest.raises(SystemExit, match="no non-wrapper op events"):
        profile_breakdown.report(str(tmp_path), top=5)


def test_report_exits_when_no_xla_ops_line(tmp_path):
    _build_xspace(tmp_path, line_name="Steps")  # events, but no 'XLA Ops'
    with pytest.raises(SystemExit, match="no 'XLA Ops' line"):
        profile_breakdown.report(str(tmp_path), top=5)


def test_report_exits_when_device_plane_has_no_events(tmp_path):
    xs = tf_xplane.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    plane.lines.add().name = "XLA Ops"  # line exists, zero events
    (tmp_path / "t.xplane.pb").write_bytes(xs.SerializeToString())
    with pytest.raises(SystemExit, match="no device plane with events"):
        profile_breakdown.report(str(tmp_path), top=5)
