"""Fleet layer suite (`tpu_dp/obs/fleet.py` + `obsctl fleet`, ISSUE 20).

Three layers of evidence: units for the shared tail reader and the
threaded stream tailer; alignment/derivation units for the aggregator
(newest-attempt-wins across guard-rollback generations AND elastic
membership epochs — no stale-world skew), the anomaly-rule window math,
and the publish/read schema contract; then CLI acceptance — a synthetic
straggler run where `obsctl fleet --replay` must exit 1 naming the
injected rank under both rule grammars while the clean twin exits 0,
the live tailing path over a growing run, and a 3-OS-process smoke
driving the real `TPU_DP_FAULT` delay injector through real heartbeat
writers across a process boundary.
"""

import json
import threading
import time
from pathlib import Path

import pytest

from tpu_dp.obs import obsctl
from tpu_dp.obs.counters import Counters
from tpu_dp.obs.fleet import (
    FLEET_SCHEMA,
    FleetAggregator,
    FleetPublisher,
    FleetSchemaError,
    discover_streams,
    fleet_signals,
    read_fleet_records,
    summarize,
)
from tpu_dp.obs.tail import JsonlTail, StreamTailer, read_jsonl

pytestmark = pytest.mark.fleet


# -- synthetic heartbeat trees ----------------------------------------------

BASE_MS = 5.0
#: the injected straggler: rank 2 stalls 300ms at steps 14/16/18 —
#: a ~60x leave-one-out ratio against the ~5ms healthy median.
DELAYS = {(14, 2): 300.0, (16, 2): 300.0, (18, 2): 300.0}


def _write_beats(obs_dir: Path, world: int = 3, steps: int = 20,
                 delays: dict | None = None, gen: int = 0,
                 me_stamp: int = 0, start: int = 0) -> None:
    """Per-rank heartbeat files with cumulative per-rank wall clocks:
    rank r's step takes BASE_MS + r*0.1 ms (+ any injected delay), so
    skew/ratio/slowest attribution are all exactly computable."""
    delays = delays or {}
    obs_dir.mkdir(parents=True, exist_ok=True)
    for rank in range(world):
        t = 1000.0
        lines = []
        for step in range(start, start + steps):
            ms = BASE_MS + rank * 0.1 + delays.get((step, rank), 0.0)
            t += ms / 1e3
            rec = {"rank": rank, "step": step, "ts": round(t, 6),
                   "step_ms": round(ms, 3)}
            if gen:
                rec["gen"] = gen
            if me_stamp:
                rec["me"] = me_stamp
            lines.append(json.dumps(rec))
        (obs_dir / f"heartbeat_r{rank:05d}.jsonl").write_text(
            "\n".join(lines) + "\n")


def _beat(rank, step, ts, step_ms, gen=None, me=None):
    rec = {"rank": rank, "step": step, "ts": ts, "step_ms": step_ms}
    if gen is not None:
        rec["gen"] = gen
    if me is not None:
        rec["me"] = me
    return rec


@pytest.fixture
def faulty_run(tmp_path):
    run = tmp_path / "faulty"
    _write_beats(run / "obs", delays=DELAYS)
    return run


@pytest.fixture
def clean_run(tmp_path):
    run = tmp_path / "clean"
    _write_beats(run / "obs")
    return run


# -- JsonlTail: the shared byte-offset reader -------------------------------

def test_tail_partial_trailing_line_deferred(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text('{"a": 1}\n{"a": 2')  # writer mid-append
    tail = JsonlTail(p)
    assert tail.poll() == [{"a": 1}]
    assert tail.poll() == []           # the torn half stays unread
    with open(p, "a") as f:
        f.write('2}\n{"a": 3}\n')
    assert tail.poll() == [{"a": 22}, {"a": 3}]


def test_tail_truncation_resets_to_top(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text('{"a": 1}\n{"a": 2}\n')
    tail = JsonlTail(p)
    assert len(tail.poll()) == 2
    p.write_text('{"b": 9}\n')         # rotate/truncate: smaller file
    assert tail.poll() == [{"b": 9}]   # offset reset, not EOF garbage


def test_tail_garbage_lines_skipped_and_missing_file(tmp_path):
    p = tmp_path / "s.jsonl"
    assert JsonlTail(p).poll() == []   # not yet created: no error
    p.write_text('{"a": 1}\nnot json\n[1, 2]\n{"a": 2}\n')
    # torn/garbage and non-dict lines skipped, offset still advances
    tail = JsonlTail(p)
    assert tail.poll() == [{"a": 1}, {"a": 2}]
    assert tail.poll() == []
    assert read_jsonl(p) == [{"a": 1}, {"a": 2}]


# -- StreamTailer: N streams, one poll thread -------------------------------

def test_stream_tailer_add_idempotent_meta_threading(tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    a.write_text('{"x": 1}\n')
    b.write_text('{"y": 1}\n')
    tailer = StreamTailer()
    assert tailer.add(a, ("hb", 0)) is True
    assert tailer.add(a, ("hb", 0)) is False   # already registered
    assert tailer.add(b, ("hb", 1)) is True
    assert sorted(tailer.paths) == sorted([a, b])
    assert tailer.poll_once() == 2
    got = tailer.drain()
    assert (("hb", 0), {"x": 1}) in got and (("hb", 1), {"y": 1}) in got
    assert tailer.drain() == []                # drained means drained


def test_stream_tailer_bounded_buffer_drops_oldest(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text("".join(json.dumps({"i": i}) + "\n" for i in range(10)))
    tailer = StreamTailer(max_buffer=4)
    tailer.add(p)
    tailer.poll_once()
    assert tailer.dropped == 6
    got = [rec["i"] for _, rec in tailer.drain()]
    assert got == [6, 7, 8, 9]                 # newest survive


def test_stream_tailer_thread_lifecycle(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text('{"i": 0}\n')
    with StreamTailer(interval_s=0.05) as tailer:
        tailer.add(p)
        with open(p, "a") as f:
            f.write('{"i": 1}\n')
        deadline = time.monotonic() + 5.0
        seen = []
        while len(seen) < 2 and time.monotonic() < deadline:
            seen.extend(rec["i"] for _, rec in tailer.drain())
            time.sleep(0.02)
        assert seen == [0, 1]
    # context exit joined the thread; stop() again is a no-op
    assert tailer._thread is None
    tailer.stop()
    assert not any(t.name == "obs-stream-tailer"
                   for t in threading.enumerate())


# -- stream discovery -------------------------------------------------------

def test_discover_streams_full_tree(tmp_path):
    run = tmp_path / "run"
    (run / "obs" / "me0001").mkdir(parents=True)
    (run / "metrics.jsonl").write_text("{}\n")
    (run / "obs" / "heartbeat_r00000.jsonl").write_text("{}\n")
    (run / "obs" / "me0001" / "heartbeat_r00001.jsonl").write_text("{}\n")
    (run / "obs" / "replica_r00000.jsonl").write_text("{}\n")
    (run / "obs" / "serve_router.jsonl").write_text("{}\n")
    got = {(kind, tuple(sorted(meta.items())))
           for kind, meta, _ in discover_streams(run)}
    assert got == {
        ("metrics", ()),
        ("heartbeat", (("me", 0), ("rank", 0))),
        ("heartbeat", (("me", 1), ("rank", 1))),
        ("replica", (("sid", 0),)),
        ("router", ()),
    }


def test_discover_streams_bare_heartbeat_tree(tmp_path):
    # a HeartbeatWriter-only dir (no obs/ nesting) still discovers
    _write_beats(tmp_path, world=2, steps=1)
    kinds = [(k, m.get("rank")) for k, m, _ in discover_streams(tmp_path)]
    assert kinds == [("heartbeat", 0), ("heartbeat", 1)]


# -- aggregation: alignment + derivation ------------------------------------

def test_emits_only_once_expected_world_reported():
    agg = FleetAggregator("/nonexistent")
    for rank in range(3):
        agg.note_stream("heartbeat", {"me": 0, "rank": rank})
    # two of three known ranks in: no emission — a step published with a
    # not-yet-read rank missing would mis-attribute the skew
    assert agg.ingest("heartbeat", {"me": 0}, _beat(0, 0, 10.0, 5.0)) == []
    assert agg.ingest("heartbeat", {"me": 0}, _beat(1, 0, 10.001, 5.0)) == []
    recs = agg.ingest("heartbeat", {"me": 0}, _beat(2, 0, 10.295, 300.0))
    assert len(recs) == 1 and recs[0]["world"] == 3


def test_skew_math_and_attribution():
    agg = FleetAggregator("/nonexistent", expected_world=3)
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 7, 10.0, 5.0))
    agg.ingest("heartbeat", {"me": 0}, _beat(1, 7, 10.001, 5.0))
    (rec,) = agg.ingest("heartbeat", {"me": 0}, _beat(2, 7, 10.295, 300.0))
    assert rec["kind"] == "fleet_step" and rec["schema"] == FLEET_SCHEMA
    assert rec["step"] == 7 and rec["ranks"] == [0, 1, 2]
    assert rec["step_skew_ms"] == pytest.approx(295.0, abs=0.01)
    assert rec["slowest_rank"] == 2
    assert rec["median_other_ms"] == 5.0       # leave-one-out median
    assert rec["skew_ratio"] == pytest.approx(60.0)
    assert rec["step_time_ms"] == 300.0        # fleet clock = slowest
    assert rec["spike"] is True                # 60 >= default 3.0
    assert rec["ts"] == 10.295                 # last arrival


def test_min_step_ms_floor_suppresses_jitter_ratios():
    # µs-scale steps: 0.5ms over a 0.001ms median would read as 500x —
    # the floor (same as HealthMonitor's) keeps jitter out of the pager
    agg = FleetAggregator("/nonexistent", expected_world=2, min_step_ms=1.0)
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 0, 10.0, 0.001))
    (rec,) = agg.ingest("heartbeat", {"me": 0}, _beat(1, 0, 10.0, 0.5))
    assert rec["skew_ratio"] == pytest.approx(0.5)
    assert rec["spike"] is False


def test_slowest_streak_persistence():
    agg = FleetAggregator("/nonexistent", expected_world=2)
    streaks = []
    slow = [1, 1, 1, 0]                        # rank 1 thrice, then rank 0
    for step, victim in enumerate(slow):
        agg.ingest("heartbeat", {"me": 0},
                   _beat(1 - victim, step, 10.0 + step, 5.0))
        (rec,) = agg.ingest("heartbeat", {"me": 0},
                            _beat(victim, step, 10.0 + step, 50.0))
        streaks.append((rec["slowest_rank"], rec["slowest_streak"]))
    assert streaks == [(1, 1), (1, 2), (1, 3), (0, 1)]


def test_rollback_generation_newest_attempt_wins():
    agg = FleetAggregator("/nonexistent", expected_world=2)
    # gen-0 attempt at step 6 emits…
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 6, 10.0, 5.0))
    (first,) = agg.ingest("heartbeat", {"me": 0}, _beat(1, 6, 10.0, 5.0))
    assert first["gen"] == 0
    # …the replay attempt (gen 1, post-rollback) supersedes it…
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 6, 20.0, 5.0, gen=1))
    (replay,) = agg.ingest("heartbeat", {"me": 0},
                           _beat(1, 6, 20.0, 5.0, gen=1))
    assert replay["gen"] == 1
    # …and a STALE gen-0 straggler completing late must never emit over
    # the newer attempt (no stale-world skew)
    agg2 = FleetAggregator("/nonexistent", expected_world=2)
    agg2.ingest("heartbeat", {"me": 0}, _beat(0, 6, 20.0, 5.0, gen=1))
    agg2.ingest("heartbeat", {"me": 0}, _beat(1, 6, 20.0, 5.0, gen=1))
    agg2.ingest("heartbeat", {"me": 0}, _beat(0, 6, 10.0, 5.0))
    assert agg2.ingest("heartbeat", {"me": 0}, _beat(1, 6, 99.0, 5.0)) == []
    assert agg2.flush() == []                  # and not resurrected later


def test_elastic_regroup_no_stale_world_skew(tmp_path):
    """A 3-rank epoch-0 world re-homes to a 2-rank me0001/ world across
    steps 4..9; the me-1 records must align only among themselves (world
    2) and win the overlap steps, and a stale epoch-0 group arriving
    after the epoch-1 emission must be dropped."""
    run = tmp_path / "run"
    _write_beats(run / "obs", world=3, steps=6)                 # steps 0..5
    _write_beats(run / "obs" / "me0001", world=2, steps=6,
                 me_stamp=1, start=4)                           # steps 4..9
    recs = FleetAggregator(run).replay()
    by_step: dict[int, dict] = {}
    for r in recs:                             # newest attempt wins
        cur = by_step.get(r["step"])
        if cur is None or (r["me"], r["gen"]) > (cur["me"], cur["gen"]):
            by_step[r["step"]] = r
    # overlap steps surface the NEW world's alignment, never a mixed one
    for step in (4, 5):
        assert by_step[step]["me"] == 1
        assert by_step[step]["world"] == 2
        assert by_step[step]["ranks"] == [0, 1]
    for step in (0, 1, 2, 3):
        assert by_step[step]["me"] == 0 and by_step[step]["world"] == 3
    assert all(by_step[s]["me"] == 1 for s in range(6, 10))
    # direct ingest order-invariance: epoch-1 emitted first, the full
    # stale epoch-0 group completing afterwards must not emit
    agg = FleetAggregator("/nonexistent")
    agg.note_stream("heartbeat", {"me": 1, "rank": 0})
    agg.note_stream("heartbeat", {"me": 1, "rank": 1})
    for rank in range(3):
        agg.note_stream("heartbeat", {"me": 0, "rank": rank})
    agg.ingest("heartbeat", {"me": 1}, _beat(0, 4, 30.0, 5.0, me=1))
    assert agg.ingest("heartbeat", {"me": 1},
                      _beat(1, 4, 30.0, 5.0, me=1)) != []
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 4, 10.0, 5.0))
    agg.ingest("heartbeat", {"me": 0}, _beat(1, 4, 10.0, 5.0))
    assert agg.ingest("heartbeat", {"me": 0},
                      _beat(2, 4, 25.0, 5.0)) == []


def test_flush_emits_best_remaining_attempt_only():
    agg = FleetAggregator("/nonexistent", expected_world=3)
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 3, 10.0, 5.0))
    agg.ingest("heartbeat", {"me": 0}, _beat(1, 3, 10.0, 5.0))  # 2 of 3
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 4, 11.0, 5.0))  # 1 of 3
    out = agg.flush()
    assert [r["step"] for r in out] == [3]     # a lone rank has no median
    assert out[0]["world"] == 2


def test_replay_attributes_injected_straggler(faulty_run, clean_run):
    recs = FleetAggregator(faulty_run).replay()
    steps = [r for r in recs if r["kind"] == "fleet_step"]
    assert len(steps) == 20
    spikes = [r for r in steps if r["spike"]]
    assert [r["step"] for r in spikes] == [14, 16, 18]
    assert all(r["slowest_rank"] == 2 for r in spikes)
    assert all(r["skew_ratio"] > 50 for r in spikes)
    rep = summarize(recs)
    assert rep["slowest_rank"] == 2 and rep["spikes"] == 3
    assert rep["max_skew_step"] in (14, 16, 18)
    clean = summarize(FleetAggregator(clean_run).replay())
    assert clean["spikes"] == 0 and clean["max_skew_ratio"] < 1.5


def test_metrics_gauges_ride_along():
    agg = FleetAggregator("/nonexistent", expected_world=2)
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 0, 10.0, 5.0))
    (bare,) = agg.ingest("heartbeat", {"me": 0}, _beat(1, 0, 10.0, 5.0))
    assert "mfu" not in bare and "goodput" not in bare   # never fabricated
    agg.ingest("metrics", {}, {"mfu": 0.41,
                               "counters": {"obs.goodput": 0.87}})
    agg.ingest("heartbeat", {"me": 0}, _beat(0, 1, 11.0, 5.0))
    (rec,) = agg.ingest("heartbeat", {"me": 0}, _beat(1, 1, 11.0, 5.0))
    assert rec["mfu"] == 0.41 and rec["goodput"] == 0.87
    assert fleet_signals(rec)["fleet.mfu"] == 0.41


def test_serve_rollup_worst_class_attainment():
    agg = FleetAggregator("/nonexistent")
    agg.ingest("replica", {"sid": 0}, {"kind": "replica", "status": "live"})
    agg.ingest("replica", {"sid": 1},
               {"kind": "replica", "status": "quarantined"})
    (rec,) = agg.ingest("router", {}, {
        "kind": "router", "ts": 50.0, "queue_depth": 7, "replicas_live": 1,
        "classes": {"0": {"attainment": 0.95}, "1": {"attainment": 0.7}},
    })
    assert rec["kind"] == "fleet_serve" and rec["queue_depth"] == 7
    assert rec["attainment"] == 0.7            # worst class, not average
    assert rec["replica_status"] == {"live": 1, "quarantined": 1}
    sig = fleet_signals(rec)
    assert sig == {"fleet.queue_depth": 7.0, "fleet.attainment": 0.7}


# -- publication + schema contract ------------------------------------------

def test_publisher_stream_gauges_and_promfile(tmp_path, faulty_run):
    out, prom = tmp_path / "fleet.jsonl", tmp_path / "fleet.prom"
    reg = Counters()
    pub = FleetPublisher(out, prom_path=prom, registry=reg)
    recs = FleetAggregator(faulty_run).replay()
    pub.publish(recs)
    assert pub.published == len(recs)
    assert read_fleet_records(out) == recs     # schema-stamped round trip
    snap = reg.snapshot()
    assert snap["fleet.slowest_rank"] == 2.0
    assert snap["fleet.skew_ratio"] == recs[-1]["skew_ratio"]  # last write
    assert snap["fleet.step_time_p95_ms"] > 100   # window holds the spikes
    assert prom.exists() and "fleet" in prom.read_text()


def test_publisher_swallows_failures_into_counter(tmp_path):
    (tmp_path / "blocked").write_text("a file, not a directory")
    reg = Counters()
    pub = FleetPublisher(tmp_path / "blocked" / "fleet.jsonl", registry=reg)
    rec = {"schema": FLEET_SCHEMA, "kind": "fleet_step", "ts": 1.0,
           "step": 0, "slowest_rank": 0, "skew_ratio": 1.0}
    pub.publish([rec])                         # must not raise
    assert reg.get("fleet.publish_errors") == 1
    assert pub.published == 0


def test_unknown_schema_is_refused_strict_but_skipped_forensic(
        tmp_path, capsys):
    p = tmp_path / "obs" / "fleet.jsonl"
    p.parent.mkdir(parents=True)
    good = {"schema": FLEET_SCHEMA, "kind": "fleet_step", "ts": 1.0,
            "step": 0, "slowest_rank": 0}
    alien = {"schema": "tpu_dp.obs/fleet/v999", "kind": "fleet_step"}
    p.write_text(json.dumps(good) + "\n" + json.dumps(alien) + "\n")
    with pytest.raises(FleetSchemaError, match="v999"):
        read_fleet_records(p)                  # strict consumer: refuse
    art = obsctl.RunArtifacts(tmp_path)
    # forensic reader: skips ONLY the alien record, keeps the readable one
    assert art.fleet_records() == [good]
    assert "unknown schema" in capsys.readouterr().err   # …and says so


# -- watch grammar: fleet signals + anomaly rules ---------------------------

def test_fleet_signals_are_first_class_rule_targets():
    r = obsctl.WatchRule("fleet.skew_ratio>1.5")
    assert (r.kind, r.signal, r.op, r.const) == (
        "threshold", "fleet.skew_ratio", ">", 1.5)
    assert obsctl.WatchRule("fleet.queue_depth>=10").signal == \
        "fleet.queue_depth"
    with pytest.raises(ValueError, match="unknown signal"):
        obsctl.WatchRule("fleet.bogus>1")


def test_anomaly_rule_parsing():
    r = obsctl.WatchRule("anomaly:step_time_ms 4")
    assert (r.kind, r.signal, r.deviations) == ("anomaly", "step_time_ms",
                                                4.0)
    assert obsctl.WatchRule("anomaly:fleet.skew_ratio 2.5").deviations == 2.5
    for bad in ("anomaly:step_time_ms",        # no K
                "anomaly:step_time_ms 0",      # zero deviations
                "anomaly:step_time_ms -3",     # negative
                "anomaly:nope 4"):             # unknown signal
        with pytest.raises(ValueError):
            obsctl.WatchRule(bad)


def _feed(engine, values):
    for i, v in enumerate(values):
        engine.observe_record({"kind": "fleet_step", "schema": FLEET_SCHEMA,
                               "step": i, "ts": float(i),
                               "step_time_ms": float(v)})


def test_anomaly_needs_min_history_before_scoring():
    eng = obsctl.WatchEngine([obsctl.WatchRule("anomaly:step_time_ms 4")],
                             None)
    # a spike before ANOMALY_MIN_POINTS of history never scores — and the
    # rule counts as never-evaluated (the exit-2 refuse-to-certify path)
    _feed(eng, [100.0] * (eng.ANOMALY_MIN_POINTS - 1) + [1000.0])
    assert eng.alerts == [] and eng.evaluated == set()
    _feed(eng, [100.0])                        # window now at min points
    assert eng.evaluated and eng.alerts == []


def test_anomaly_trips_at_k_robust_deviations_not_below():
    # 12 identical points: MAD 0, so sigma = REL_FLOOR * |median| = 5.0;
    # K=4 puts the bound exactly at 100 ± 20
    eng = obsctl.WatchEngine([obsctl.WatchRule("anomaly:step_time_ms 4")],
                             None)
    _feed(eng, [100.0] * 12)
    _feed(eng, [119.0])                        # score 3.8 < 4
    assert eng.alerts == []
    _feed(eng, [121.0])                        # score 4.2 > 4
    assert len(eng.alerts) == 1
    ev = eng.alerts[0]
    assert ev["signal"] == "step_time_ms" and ev["value"] == 121.0
    assert ev["score"] == pytest.approx(4.2)
    assert ev["median"] == 100.0 and ev["bound"] == pytest.approx(120.0)


def test_anomaly_spike_does_not_baseline_itself():
    eng = obsctl.WatchEngine([obsctl.WatchRule("anomaly:step_time_ms 4")],
                             None)
    _feed(eng, [100.0] * 12)
    _feed(eng, [300.0])                        # scored BEFORE joining
    _feed(eng, [300.0])                        # the window: still vs ~100
    assert len(eng.alerts) == 2
    _feed(eng, [100.0])                        # back to normal: no trip
    assert len(eng.alerts) == 2


# -- profile-derived rules (obsctl watch --profile) -------------------------

def _tuned(tmp_path, claims):
    from tpu_dp.tune.profile import build_profile, dump_profile, make_key

    path = tmp_path / "tuned.json"
    dump_profile(build_profile(
        key=make_key("resnet18", 8, "cpu"), knobs={}, claims=claims,
        objective={"metric": "img_per_sec_per_chip", "value": 123.0},
        provenance={"seed": 1}), path)
    return path


def test_profile_rules_derivation(tmp_path):
    path = _tuned(tmp_path, {
        "mfu": 0.5, "goodput": 0.9, "overlap_frac": 0.8,
        "comm_ms": 10.0, "exposed_comm_ms": 2.0, "p95_ms": 50.0,
        "img_per_sec_per_chip": 123.0,
    })
    texts = {r.text for r in obsctl.profile_rules(path, tolerance=0.2)}
    assert texts == {
        "mfu<0.4", "goodput<0.72", "overlap_frac<0.64",
        "comm_ms>12.0", "exposed_comm_ms>2.4", "step_time_ms>60.0",
    }
    # throughput has no stream twin: deliberately derives NO rule
    assert not any("img_per_sec" in t for t in texts)


def test_watch_profile_flag_end_to_end(tmp_path, capsys):
    path = _tuned(tmp_path, {"mfu": 0.5})
    run = tmp_path / "run"
    run.mkdir()
    (run / "metrics.jsonl").write_text("".join(
        json.dumps({"step": i, "ts": float(i), "mfu": 0.1}) + "\n"
        for i in range(3)))
    assert obsctl.main(["watch", str(run), "--replay",
                        "--profile", str(path)]) == 1   # claim violated
    capsys.readouterr()
    (run / "metrics.jsonl").write_text(
        json.dumps({"step": 0, "ts": 0.0, "mfu": 0.5}) + "\n")
    assert obsctl.main(["watch", str(run), "--replay",
                        "--profile", str(path)]) == 0
    capsys.readouterr()
    bad = tmp_path / "not_a_profile.json"
    bad.write_text('{"schema": "something/else"}')
    assert obsctl.main(["watch", str(run), "--replay",
                        "--profile", str(bad)]) == 2    # typed refusal
    assert "schema" in capsys.readouterr().err


# -- obsctl fleet CLI -------------------------------------------------------

def test_cmd_fleet_replay_names_injected_rank(faulty_run, clean_run,
                                              tmp_path, capsys):
    """The CI gate in both directions: the straggler run exits 1 with BOTH
    rule grammars tripping and the report naming the injected rank; the
    clean twin — same rules, same thresholds — exits 0."""
    report = tmp_path / "fleet_report.json"
    rc = obsctl.main(["fleet", str(faulty_run), "--replay", "--json",
                      "--rule", "fleet.skew_ratio>3",
                      "--rule", "anomaly:step_time_ms 4",
                      "--report", str(report)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["report"]["slowest_rank"] == 2
    assert out["report"]["spikes"] == 3
    tripped = {ev["rule"] for ev in out["alerts"]}
    assert tripped == {"fleet.skew_ratio>3", "anomaly:step_time_ms 4"}
    assert sorted(out["evaluated"]) == sorted(tripped)
    # the published stream + the archived report are both readable
    assert json.loads(report.read_text())["slowest_rank"] == 2
    published = read_fleet_records(faulty_run / "obs" / "fleet.jsonl")
    assert len(published) == out["published"] == 20

    rc = obsctl.main(["fleet", str(clean_run), "--replay", "--json",
                      "--rule", "fleet.skew_ratio>3",
                      "--rule", "anomaly:step_time_ms 4"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["alerts"] == [] and len(out["evaluated"]) == 2


def test_cmd_fleet_exit_codes_on_degenerate_input(tmp_path, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obsctl.main(["fleet", str(empty), "--replay"]) == 2  # no streams
    capsys.readouterr()
    assert obsctl.main(["fleet", str(empty), "--replay",
                        "--rule", "fleet.bogus>1"]) == 2        # bad rule
    assert "unknown signal" in capsys.readouterr().err


def test_watch_fleet_rule_aggregates_from_raw_artifacts(faulty_run,
                                                        clean_run, capsys):
    # no published fleet.jsonl: watch --replay must derive the fleet
    # stream from the heartbeats itself
    assert obsctl.main(["watch", str(faulty_run), "--replay",
                        "--rule", "fleet.skew_ratio>3"]) == 1
    capsys.readouterr()
    assert obsctl.main(["watch", str(clean_run), "--replay",
                        "--rule", "fleet.skew_ratio>3"]) == 0
    capsys.readouterr()


def test_timeline_markers_and_trace_counter_track(faulty_run, tmp_path,
                                                  capsys):
    # publish the fleet stream, then the forensic surfaces must carry it
    assert obsctl.main(["fleet", str(faulty_run), "--replay"]) == 0
    capsys.readouterr()
    rc = obsctl.main(["timeline", str(faulty_run), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    marks = [e for e in out["events"] if e["kind"] == "fleet_skew"]
    assert [e["step"] for e in marks] == [14, 16, 18]
    assert all(e["rank"] == 2 for e in marks)
    assert all(e["detail"]["skew_ratio"] > 50 for e in marks)
    assert out["stats"]["sources"]["fleet"] is True

    trace_path = tmp_path / "merged.json"
    assert obsctl.main(["merge-trace", str(faulty_run), "-o",
                        str(trace_path)]) == 0
    trace = json.loads(trace_path.read_text())
    counters = [e for e in trace["traceEvents"]
                if e.get("ph") == "C" and e["name"] == "fleet.step_skew_ms"]
    assert len(counters) == 20
    assert all(e["pid"] == 999_000 for e in counters)
    procs = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "fleet" in procs
    assert any(e.get("ph") == "i" and e["name"] == "fleet_skew"
               for e in trace["traceEvents"])


def test_cmd_fleet_live_tails_growing_run(tmp_path, capsys):
    """The live path: ranks append heartbeats WHILE `obsctl fleet` tails —
    the injected stall must trip both rule grammars live."""
    run = tmp_path / "run"
    obs = run / "obs"
    obs.mkdir(parents=True)

    def writer():
        files = [open(obs / f"heartbeat_r{r:05d}.jsonl", "a")
                 for r in range(3)]
        t = [1000.0] * 3
        try:
            for step in range(15):
                for r, f in enumerate(files):
                    ms = BASE_MS + r * 0.1 + (300.0 if (step, r) == (10, 2)
                                              else 0.0)
                    t[r] += ms / 1e3
                    f.write(json.dumps({"rank": r, "step": step,
                                        "ts": t[r], "step_ms": ms}) + "\n")
                    f.flush()
                time.sleep(0.05)
        finally:
            for f in files:
                f.close()

    th = threading.Thread(target=writer)
    th.start()
    try:
        rc = obsctl.main(["fleet", str(run), "--json",
                          "--for-s", "3.0", "--interval", "0.2",
                          "--rule", "fleet.skew_ratio>3",
                          "--rule", "anomaly:step_time_ms 4"])
    finally:
        th.join()
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["report"]["steps"] == 15
    assert out["report"]["slowest_rank"] == 2
    assert out["report"]["max_skew_step"] == 10
    assert {ev["rule"] for ev in out["alerts"]} == {
        "fleet.skew_ratio>3", "anomaly:step_time_ms 4"}


# -- 3-OS-process smoke: the real injector across a process boundary --------

_FLEET_WORKER = r"""
import sys, time
rank = int(sys.argv[1]); run_dir = sys.argv[2]; spec = sys.argv[3]
from tpu_dp.obs.health import HeartbeatWriter
from tpu_dp.resilience.faultinject import FaultInjector

inj = FaultInjector.from_spec(spec, rank=rank) if spec != "-" else None
with HeartbeatWriter(run_dir, rank=rank) as hb:
    for step in range(1, 13):
        t0 = time.perf_counter()
        time.sleep(0.02)               # uniform simulated step work
        if inj is not None:
            inj.on_step(step)          # the injected straggler stall
        hb.beat(step, (time.perf_counter() - t0) * 1e3)
print("FLEET_OK", rank, flush=True)
"""


def test_three_process_delay_fault_fleet_attribution(tmp_path, monkeypatch,
                                                     capsys):
    """End-to-end across real process boundaries: three OS processes
    heartbeat through the production writer, the production TPU_DP_FAULT
    delay injector stalls rank 2 at step 10, and `obsctl fleet --replay`
    must exit 1 naming exactly that rank — while the clean twin, same
    rules, exits 0."""
    from test_multiprocess import _spawn_workers

    monkeypatch.delenv("TPU_DP_FAULT", raising=False)
    faulty, clean = tmp_path / "faulty", tmp_path / "clean"
    spec = "delay:step=10,rank=2,ms=300"
    logs = _spawn_workers(
        tmp_path, _FLEET_WORKER,
        [(rank, faulty / "obs", spec) for rank in range(3)],
        name="fleet_faulty", timeout=120)
    assert all("FLEET_OK" in log for log in logs)
    logs = _spawn_workers(
        tmp_path, _FLEET_WORKER,
        [(rank, clean / "obs", "-") for rank in range(3)],
        name="fleet_clean", timeout=120)
    assert all("FLEET_OK" in log for log in logs)

    # generous thresholds: real scheduler jitter rides on ~20ms steps, and
    # a clean trip would make the gate a coin flip — the injected stall is
    # a ~16x ratio / ~90-sigma excursion, far above either bound
    rules = ["--rule", "fleet.skew_ratio>5",
             "--rule", "anomaly:step_time_ms 12"]
    rc = obsctl.main(["fleet", str(faulty), "--replay", "--json", *rules])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["report"]["max_skew_step"] == 10
    assert out["report"]["max_skew_ratio"] >= 5.0
    assert {ev["rule"] for ev in out["alerts"]} == set(rules[1::2])
    # the worst-skew record names the injected rank, across real processes
    published = read_fleet_records(faulty / "obs" / "fleet.jsonl")
    worst = max(published, key=lambda r: r.get("skew_ratio", 0.0))
    assert (worst["step"], worst["slowest_rank"]) == (10, 2)
    assert worst["step_time_ms"] >= 300.0      # carries the delay
    rc = obsctl.main(["fleet", str(clean), "--replay", "--json", *rules])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["alerts"] == []
