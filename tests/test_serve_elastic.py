"""The self-healing serving tier (ISSUE 11, docs/SERVING.md).

What must hold, in order of importance:

1. **Exact books through chaos**: the loadgen's caller-vs-counter audit
   (accepted / completed / shed-per-reason / deadline-missed, overall AND
   per SLO class) and the cross-replica device-side served count stay
   exactly consistent through replica failover, quarantine, drain,
   rejoin, and hot swap — zero dropped, zero double-served.
2. **Typed failure**: a dead replica's in-flight requests are retried on
   a survivor or shed with reason ``replica_failed`` — never silently
   dropped, never silently re-counted.
3. **Elastic membership**: drain-then-leave and rejoin are published as
   serving-flavored membership epochs in the PR 7 ledger format, and
   ``obsctl timeline`` reconstructs drain → failover → swap from the run
   directory's artifacts alone.
4. **Hot swap**: versioned in-place weight updates between batches, every
   response stamped with the version that served it.
"""

from __future__ import annotations

import json
import os
import signal
import time

import numpy as np
import pytest

import jax

from tpu_dp.serve import ServeCluster, arrival_offsets, run_load

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def net_model():
    from tpu_dp.models import build_model

    model = build_model("net")
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    return model, variables["params"]


def make_cluster(net_model, **kw):
    model, params = net_model
    kw.setdefault("replicas", 2)
    kw.setdefault("buckets", (1, 2, 4))
    kw.setdefault("slo_ms", 5000.0)
    kw.setdefault("health_every_s", 0.02)
    return ServeCluster(model, params, **kw)


def _wait_for(predicate, timeout_s=10.0, poll_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return False


# -- fan-out basics ---------------------------------------------------------

def test_cluster_two_replicas_exact_books_and_class_mix(net_model):
    """120 mixed-size, mixed-class requests over 2 replicas (4 devices
    each): exact overall AND per-class books, zero retraces, every batch
    attributed to a replica, device-side served = caller-side served."""
    cluster = make_cluster(net_model, class_slo_ms={0: 5000.0, 1: 8000.0})
    with cluster:
        report = run_load(
            cluster, n_requests=120, pattern="poisson", rate_rps=600.0,
            sizes=(1, 2, 3), seed=1, class_mix=(0.6, 0.4),
        )
    truth = report["ground_truth"]
    assert report["consistent"], (truth, report["counters"])
    assert truth["completed"] == truth["accepted"] == 120
    assert truth["unresolved"] == 0
    assert set(truth["by_class"]) == {0, 1}
    assert report["retraces"] == 0
    assert set(report["classes"]) <= {"0", "1"}
    assert report["classes"]["0"]["slo_ms"] == 5000.0
    assert report["classes"]["1"]["slo_ms"] == 8000.0
    per_replica = report["replicas"]
    assert len(per_replica) == 2
    assert sum(r["batches"] for r in per_replica.values()) \
        == report["batches"]
    assert report["device_stats"]["served"] == truth["images_served"]
    assert sum(report["device_stats"]["class_counts"]) \
        == truth["images_served"]
    assert report["world"] == 8  # 2 replicas x 4 devices


def test_cluster_from_serve_config(net_model):
    from tpu_dp.config import ServeConfig

    model, params = net_model
    cluster = ServeCluster.from_serve_config(
        model, params,
        ServeConfig(replicas=2, buckets="1,2", slo_ms=99.0,
                    class_slo_ms="99,200", stale_after_s=1.25),
    )
    assert cluster.n_replicas == 2
    assert cluster.ladder.buckets == (1, 2)
    assert cluster.class_slo_ms == {0: 99.0, 1: 200.0}
    assert cluster.stale_after_s == 1.25


# -- failover (ISSUE 11 satellite: delay-poisoned + killed in one run) ------

def test_failover_bookkeeping_slow_plus_dead_replica(net_model):
    """One replica delay-poisoned (TPU_DP_FAULT grammar, rank=sid), the
    other killed mid-run by a raising program: the dead replica's
    in-flight requests are retried on the survivor, accepted ==
    completed + shed(per-reason), and the device-side served count equals
    the caller count — zero double-served requests."""
    cluster = make_cluster(
        net_model,
        fault="delay:step=2,ms=300,rank=0",
        stale_after_s=30.0,  # quarantine not under test here
        max_retries=1,
    )
    cluster.start()

    def boom(*a, **k):
        raise RuntimeError("injected replica death")

    for bucket in cluster.replicas[1]._programs:
        cluster.replicas[1]._programs[bucket] = boom
    report = run_load(
        cluster, n_requests=60, pattern="poisson", rate_rps=400.0,
        sizes=(1, 2), seed=3,
    )
    cluster.stop()  # must NOT raise: the survivor absorbed the failure
    truth = report["ground_truth"]
    assert report["replicas"]["1"]["status"] == "dead"
    assert report["replica_errors"] and \
        "injected replica death" in report["replica_errors"][0]["error"]
    # The dead replica had an in-flight batch; its requests were retried.
    assert report["counters"].get("serve.failover.retried", 0) >= 1
    assert report["consistent"], (truth, report["counters"])
    assert truth["unresolved"] == 0
    shed = truth["shed_by_reason"]
    assert set(shed) <= {"replica_failed"}, shed
    assert truth["completed"] + truth["shed"] == 60
    # Zero double-serves: device-side served across BOTH replicas equals
    # the images the callers actually saw answered.
    assert report["device_stats"]["served"] == truth["images_served"]
    # The failure is on the membership record (when a run_dir exists it
    # is also on disk; here the in-memory epoch view suffices via report).
    assert report["replicas"]["0"]["status"] in ("running", "stopped")


def test_all_replicas_dead_sheds_typed_and_stop_raises(net_model):
    """When the WHOLE tier dies, queued requests shed `replica_failed`
    (typed, counted) and stop() surfaces the failure."""
    cluster = make_cluster(net_model, max_retries=0)
    cluster.start()

    def boom(*a, **k):
        raise RuntimeError("tier wipeout")

    for r in cluster.replicas:
        for bucket in r._programs:
            r._programs[bucket] = boom
    handles = [
        cluster.submit(np.zeros((1, 32, 32, 3), np.uint8))
        for _ in range(6)
    ]
    assert _wait_for(
        lambda: all(r.status == "dead" for r in cluster.replicas)
    )
    for h in handles:
        assert h.wait(10.0)
        assert not h.ok and h.shed_reason in ("replica_failed",)
    with pytest.raises(RuntimeError, match="all 2 serve replicas failed"):
        cluster.stop()


# -- quarantine (stale heartbeat while holding work) ------------------------

def test_wedged_replica_quarantined_then_restored(net_model, tmp_path):
    """A replica wedged in a long device call (injected delay) goes
    heartbeat-stale while holding an in-flight batch: the router
    quarantines it (stops feeding), the survivor keeps serving, and the
    books stay exact; when the wedge clears it is restored. Health is
    derived from the same heartbeat files the trainer's HealthMonitor
    reads."""
    cluster = make_cluster(
        net_model,
        run_dir=str(tmp_path),
        stale_after_s=0.25,
        fault="delay:step=0,ms=1200,rank=0",
    )
    with cluster:
        handles = []
        # Keep offering singles until replica 0 takes one and wedges.
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline and \
                not cluster.replicas[0].quarantined:
            handles.append(
                cluster.submit(np.zeros((1, 32, 32, 3), np.uint8))
            )
            time.sleep(0.02)
        assert cluster.replicas[0].quarantined, \
            "router never quarantined the wedged replica"
        snap = cluster._counters.snapshot()
        assert snap.get("serve.replica_quarantine_events", 0) >= 1
        assert snap.get("serve.replica_health.0") == 0.0
        # The wedge clears (the delay is one-shot) → restored.
        assert _wait_for(lambda: not cluster.replicas[0].quarantined)
        for h in handles:
            assert h.wait(30.0) and h.ok
    snap = cluster._counters.snapshot()
    assert snap.get("serve.replica_health.0") == 1.0
    assert cluster.replicas[0].status in ("running", "stopped")
    # The heartbeat files the quarantine derived from are on disk.
    assert (tmp_path / "obs" / "heartbeat_r00000.jsonl").exists()
    assert (tmp_path / "obs" / "heartbeat_r00001.jsonl").exists()


# -- elastic drain / rejoin + the forensic timeline -------------------------

def test_drain_rejoin_swap_chaos_matrix(net_model, tmp_path):
    """The ISSUE 11 acceptance scenario, in-process: burst traffic with a
    mid-run drain of replica 1, a hot swap, and a rejoin — exact books,
    membership epochs on disk, version-stamped responses, and an obsctl
    timeline that reconstructs drain → swap → rejoin from the artifacts
    directory alone."""
    from tpu_dp.obs import flightrec

    model, params = net_model
    fresh = model.init(
        jax.random.PRNGKey(11), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    cluster = make_cluster(net_model, run_dir=str(tmp_path))
    flightrec.recorder.reset()
    flightrec.recorder.configure(
        rank=0, dump_dir=tmp_path / "obs", fresh=True,
        run={"kind": "serve-test"},
    )
    try:
        def drain():
            cluster.drain(1)

        def rejoin():
            assert _wait_for(
                lambda: cluster.replicas[1].status == "left"
            ), "drain never completed"
            cluster.rejoin(1)

        def swap():
            cluster.swap_model(fresh["params"])

        with cluster:
            report = run_load(
                cluster, n_requests=150, pattern="burst", burst=10,
                rate_rps=500.0, sizes=(1, 2), seed=4,
                class_mix=(0.7, 0.3),
                events=[(25, "drain", drain), (60, "swap", swap),
                        (100, "rejoin", rejoin)],
            )
        flightrec.recorder.dump(reason="test_exit")
    finally:
        flightrec.recorder.reset()

    truth = report["ground_truth"]
    assert report["consistent"], (truth, report["counters"])
    assert truth["unresolved"] == 0
    assert report["retraces"] == 0  # rejoin reused the compiled programs
    # Both versions actually served, and the stamps account for everything.
    assert set(truth["served_by_version"]) == {"1", "2"}
    assert sum(truth["served_by_version"].values()) == truth["completed"]
    assert report["model_version"] == 2
    # Membership: initial → departure → rejoin, in the PR 7 ledger format.
    led = sorted(
        p.name for p in (tmp_path / "membership" / "serve").glob("epoch_*")
    )
    assert led == ["epoch_0000.json", "epoch_0001.json", "epoch_0002.json"]
    e1 = json.loads(
        (tmp_path / "membership" / "serve" / "epoch_0001.json").read_text()
    )
    assert e1["members"] == [0]
    assert e1["departed"][0]["sid"] == 1
    e2 = json.loads(
        (tmp_path / "membership" / "serve" / "epoch_0002.json").read_text()
    )
    assert e2["members"] == [0, 1] and e2["reason"] == "serve_rejoin"

    # obsctl reconstructs the story from the run dir alone.
    from tpu_dp.obs.obsctl import RunArtifacts, build_timeline

    timeline = build_timeline(RunArtifacts(tmp_path))
    kinds = [e["kind"] for e in timeline["events"]]
    for expected in ("membership_formed", "serve_dispatch", "replica_drain",
                     "eviction", "model_swap", "replica_rejoin",
                     "membership_epoch"):
        assert expected in kinds, (expected, sorted(set(kinds)))
    # The drain precedes the rejoin in the merged, ordered stream.
    assert kinds.index("replica_drain") < kinds.index("replica_rejoin")


def test_sigterm_drains_one_replica(net_model):
    """Real SIGTERM to the serving process means drain-then-leave for the
    configured replica: the handler only records, the health loop drains,
    the survivor keeps serving, and the books stay exact."""
    cluster = make_cluster(net_model)
    cluster.install_sigterm_drain(sid=1)
    try:
        with cluster:
            h = cluster.submit(np.zeros((1, 32, 32, 3), np.uint8))
            assert h.wait(30.0) and h.ok
            os.kill(os.getpid(), signal.SIGTERM)
            assert _wait_for(
                lambda: cluster.replicas[1].status == "left"
            ), "SIGTERM never drained replica 1"
            # The survivor still serves.
            h2 = cluster.submit(np.zeros((1, 32, 32, 3), np.uint8))
            assert h2.wait(30.0) and h2.ok and h2.served_by == 0
    finally:
        cluster.restore_sigterm()
    snap = cluster._counters.snapshot()
    assert snap.get("preempt.signals", 0) >= 1


# -- loadgen: diurnal pattern ----------------------------------------------

def test_arrival_offsets_diurnal_ramps():
    rng = np.random.default_rng(0)
    n = 2000
    off = arrival_offsets(n, "diurnal", 1000.0, 8, rng)
    assert len(off) == n and (np.diff(off) >= 0).all() and off[0] == 0
    # Mid-run (peak) arrivals are denser than the edges (trough): compare
    # the time the first/last deciles take against the middle decile.
    d = n // 10
    edge = (off[d] - off[0]) + (off[-1] - off[-d])
    mid = off[n // 2 + d // 2] - off[n // 2 - d // 2]
    assert mid < edge / 3  # peak rate ~4x trough; generous margin
    with pytest.raises(ValueError):
        arrival_offsets(5, "diurnal", 0.0, 8, rng)


# -- obsctl: serve attainment/p95 gate (ISSUE 11 satellite) -----------------

def _serve_report_fixture(attainment0=0.95, p95=40.0):
    return {
        "slo": {"target_ms": 50.0, "attainment": 0.9},
        "latency_ms": {"p95_ms": p95, "n": 100},
        "classes": {
            "0": {"slo_ms": 50.0, "attainment": attainment0, "n": 60},
            "1": {"slo_ms": 250.0, "attainment": 0.8, "n": 40},
        },
        "counters": {"serve.accepted": 100},
        "ground_truth": {"accepted": 100},
    }


def test_obsctl_diff_gates_serve_attainment_and_p95(tmp_path, capsys):
    """`obsctl diff` gates per-class serve attainment and p95 exactly
    like MFU: exit 0 clean, 1 on regression, 2 when nothing comparable."""
    from tpu_dp.obs.obsctl import main as obsctl_main

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "serve_elastic_report.json").write_text(
        json.dumps(_serve_report_fixture())
    )
    base = tmp_path / "base.json"
    assert obsctl_main(
        ["diff", str(run_dir), "--write-baseline", str(base)]
    ) == 0
    minted = json.loads(base.read_text())
    assert minted["serve_attainment_c0"] == 0.95
    assert minted["serve_p95_ms"] == 40.0
    # Clean: run vs its own baseline.
    assert obsctl_main(
        ["diff", str(run_dir), "--baseline", str(base)]
    ) == 0
    # Regression: class-0 attainment collapses below the bound.
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    (bad_dir / "serve_elastic_report.json").write_text(
        json.dumps(_serve_report_fixture(attainment0=0.5))
    )
    assert obsctl_main(
        ["diff", str(bad_dir), "--baseline", str(base)]
    ) == 1
    # Regression: p95 blows past the tolerance band.
    slow_dir = tmp_path / "slow"
    slow_dir.mkdir()
    (slow_dir / "serve_elastic_report.json").write_text(
        json.dumps(_serve_report_fixture(p95=400.0))
    )
    assert obsctl_main(
        ["diff", str(slow_dir), "--baseline", str(base)]
    ) == 1
    # A raw serve report works as the baseline too (known-good run gates
    # the next one directly).
    assert obsctl_main(
        ["diff", str(run_dir), "--baseline",
         str(run_dir / "serve_elastic_report.json")]
    ) == 0
    # Nothing comparable: no serve report, no metrics → exit 2.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obsctl_main(
        ["diff", str(empty), "--baseline", str(base)]
    ) == 2
    capsys.readouterr()
