"""dplint Level 4 (`tpu_dp.analysis.hostproto`) — host-protocol rules.

Three layers of coverage, mirroring `tests/test_analysis.py`:

1. Adversarial fixtures (`tests/fixtures/dplint/host/`): one known-bad
   module per rule, DP401–DP405. Each marks its finding lines with
   ``# EXPECT: <RULE>`` and carries a pragma'd twin that must NOT fire;
   the test drives the real CLI (`python -m tpu_dp.analysis host` via
   `cli.main(["host", ...])`) and asserts the exit code, rule, file, and
   the EXACT finding set (a pragma'd twin firing is as much a regression
   as a violation not firing).
2. The shipped tree is clean: `python -m tpu_dp.analysis host` exits 0
   (every real violation this PR found was fixed or pragma-audited).
3. Engine unit tests for the subtle clean/flag boundaries: scope-aware
   router resolution (the same-named-closure aliasing that hid the
   checkpoint latest-pointer bug), the one-level interprocedural
   deadline proof, wall-clock-as-data non-findings, and the registry
   invariants the DP404/DP405 cross-checks import.

Fast lane: ``pytest -m lint`` (the `tools/run_tier1.sh --lint` CI lane).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import textwrap

import pytest

from tpu_dp.analysis import hostproto
from tpu_dp.analysis.cli import main as dplint_main
from tpu_dp.analysis.report import RULES
from tpu_dp.obs.counters import METRIC_FAMILIES, METRICS
from tpu_dp.obs.flightrec import KINDS

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "dplint", "host")
HOST_RULES = {r for r in RULES if r.startswith("DP4")}

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(DP\d{3})")

FIXTURE_FILES = sorted(
    f for f in os.listdir(FIXTURES) if f.endswith(".py")
)


def _expected_findings(path: str) -> list[tuple[str, int]]:
    out = []
    with open(path, encoding="utf-8") as f:
        for lineno, text in enumerate(f, start=1):
            for m in _EXPECT_RE.finditer(text):
                out.append((m.group(1), lineno))
    return out


def _run_host(capsys, argv: list[str]) -> tuple[int, dict]:
    rc = dplint_main(["host"] + argv + ["--json"])
    payload = json.loads(capsys.readouterr().out)
    return rc, payload


# -- 1. every adversarial fixture fires exactly its declared set ----------

@pytest.mark.parametrize("fixture", FIXTURE_FILES)
def test_fixture_fires_exact_expected_set(fixture, capsys):
    path = os.path.join(FIXTURES, fixture)
    expected = set(_expected_findings(path))
    assert expected, f"{fixture} declares no # EXPECT: comments"

    rc, payload = _run_host(capsys, [path])
    assert rc == 1, f"{fixture}: expected exit 1, got {rc}"
    got = {(f["rule"], f["line"]) for f in payload["findings"]}
    # Exact equality: a missing violation AND a firing pragma'd twin are
    # both regressions.
    assert got == expected, (
        f"{fixture}: expected exactly {sorted(expected)}, got {sorted(got)}"
    )
    for f in payload["findings"]:
        assert f["path"] == path
        assert f["rule"] in HOST_RULES
        assert f["message"]


def test_every_host_rule_has_a_fixture():
    covered = set()
    for fixture in FIXTURE_FILES:
        for rule, _ in _expected_findings(os.path.join(FIXTURES, fixture)):
            covered.add(rule)
    assert covered == HOST_RULES, (
        f"host rules without a fixture: {HOST_RULES - covered}"
    )


def test_host_list_rules(capsys):
    rc = dplint_main(["host", "--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rule in sorted(HOST_RULES):
        assert rule in out


# -- 2. the shipped tree is clean -----------------------------------------

def test_shipped_tree_lints_clean(capsys):
    rc, payload = _run_host(capsys, [os.path.join(REPO, "tpu_dp")])
    assert payload["findings"] == []
    assert rc == 0


def test_tampered_copy_planted_in_scratch_package_fails(tmp_path, capsys):
    """The CI lane's negative direction: a fixture copied into a scratch
    package (outside tpu_dp/, as `tools/run_tier1.sh --lint` plants it)
    must still fail with rule+file+line attribution."""
    pkg = tmp_path / "scratchpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    planted = pkg / "ledger.py"
    shutil.copy(os.path.join(FIXTURES, "dp401_unrouted_io.py"), planted)

    rc, payload = _run_host(capsys, [str(tmp_path)])
    assert rc == 1
    findings = payload["findings"]
    assert any(
        f["rule"] == "DP401" and f["path"] == str(planted) and f["line"] > 0
        for f in findings
    )


# -- 3. engine boundaries --------------------------------------------------

def _lint(src: str, path: str = "fix.py") -> list:
    return hostproto.lint_source(path, textwrap.dedent(src))


def test_dp401_same_named_closure_is_not_laundered():
    """Routing is resolved per def node, not per name: `_io(_write)` in
    one function must not exempt a DIFFERENT closure also named `_write`
    — the exact aliasing that hid the unrouted checkpoint latest-pointer
    publish from the first draft of the rule."""
    src = """
    from tpu_dp.resilience.retry import retry_call


    def _io(fn):
        return retry_call(fn, retry_on=(OSError,))


    def routed(path):
        def _write():
            path.write_text("x")

        _io(_write)


    def unrouted(path):
        def _write():
            path.write_text("x")

        _write()
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP401"]
    assert "unrouted" in findings[0].symbol or "_write" in findings[0].symbol


def test_dp401_shim_consult_routes_the_enclosing_function():
    src = """
    def _storage_shim():
        return None


    def publish(path):
        shim = _storage_shim()
        if shim is not None:
            shim.on_write(path)
        path.write_text("x")
    """
    assert _lint(src) == []


def test_dp401_read_open_is_clean_write_open_fires():
    src = """
    def load(path):
        with open(path) as f:
            return f.read()


    def store(path, text):
        with open(path, "w") as f:
            f.write(text)
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP401"]


def test_dp402_interprocedural_deadline_proof():
    """The quiesce_blocking -> quiesce_step shape: the loop's deadline
    lives one call level down in a same-module function."""
    src = """
    import time


    def step_once(state):
        now = time.monotonic()
        if now > state.started + state.timeout_s:
            raise TimeoutError("quiesce timed out")
        return state.done


    def blocking(state, poll_s):
        while True:
            if step_once(state):
                return
            time.sleep(poll_s)
    """
    assert _lint(src) == []


def test_dp402_stop_flag_wait_in_loop_test_is_exempt():
    src = """
    def health_loop(stop, every_s, check):
        while not stop.wait(every_s):
            check()
    """
    assert _lint(src) == []


def test_dp402_derived_deadline_variable_is_recognized():
    src = """
    import time


    def wait(q, timeout_s):
        end = time.perf_counter() + timeout_s
        while True:
            if q.ready():
                return True
            if time.perf_counter() >= end:
                return False
            time.sleep(0.01)
    """
    assert _lint(src) == []


def test_dp403_data_stamps_are_not_flagged():
    src = """
    import json
    import time


    def stamp(reason):
        return json.dumps({"reason": reason, "ts": time.time()}) + "\\n"


    def observe(engine, art, end_signals):
        engine.observe_state(end_signals(art, now=time.time()),
                             ts=time.time())
    """
    assert [f.rule for f in _lint(src)] == []


def test_dp403_alias_and_local_import_are_recognized():
    src = """
    def watch(for_s):
        import time as _time

        deadline = _time.time() + for_s
        return deadline
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP403"]


def test_dp404_emit_collection_feeds_rendered_check(tmp_path):
    """lint_paths aggregates emits across files: a marker kind emitted in
    ANOTHER analyzed file is not dead forensics."""
    render = tmp_path / "render.py"
    emit = tmp_path / "emit.py"
    render.write_text("MARKER_KINDS = (\n    \"profile_start\",\n)\n")
    emit.write_text(
        "from tpu_dp.obs import flightrec\n\n\n"
        "def go():\n    flightrec.record(\"profile_start\")\n"
    )
    assert hostproto.lint_paths([str(render), str(emit)]) == []
    findings = hostproto.lint_paths([str(render)])
    assert [f.rule for f in findings] == ["DP404"]
    assert "profile_start" in findings[0].message


def test_dp405_fstring_prefix_must_match_a_family():
    src = """
    from tpu_dp.obs.counters import counters


    def good(sid):
        counters.gauge(f"serve.replica_health.{sid}", 1.0)


    def bad(sid):
        counters.inc(f"zorble.{sid}")
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP405"]
    assert "zorble." in findings[0].message


# -- registries the cross-checks import ------------------------------------

def test_kind_registry_is_well_formed():
    assert KINDS, "flightrec.KINDS must not be empty"
    for kind, desc in KINDS.items():
        assert kind and kind == kind.strip()
        assert isinstance(desc, str) and desc


def test_metric_registry_is_well_formed():
    assert METRICS and METRIC_FAMILIES
    for name, desc in METRICS.items():
        assert name and "." in name, name  # dotted subsystem.metric names
        assert isinstance(desc, str) and desc
    for prefix in METRIC_FAMILIES:
        # A family prefix must not silently swallow an exact metric's
        # whole name-space typo'd: prefixes end at a separator boundary.
        assert prefix[-1] in "._" or prefix[-1].isalpha()


def test_obsctl_rendered_kinds_are_all_registered():
    """The single-source contract, asserted directly against the shipped
    renderer (belt to the lint's suspenders)."""
    from tpu_dp.obs import obsctl

    rendered = set(obsctl.MARKER_KINDS) | set(obsctl._REPLICATED_KINDS) \
        | set(obsctl._QUARANTINE_KINDS) \
        | set(obsctl._QUARANTINE_KINDS.values())
    missing = rendered - set(KINDS)
    assert not missing, f"rendered kinds missing from KINDS: {missing}"
