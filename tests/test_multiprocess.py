"""Multi-process end-to-end: 2 JAX processes over a loopback coordinator.

The TPU-native analogue of the reference's `torchrun --nproc_per_node=2`
NCCL run (`cifar_example_ddp.py:55-57`'s `127.0.0.1:29500` rendezvous):
two OS processes bootstrap via `jax.distributed.initialize`, build a shared
2-device mesh (1 CPU device each), feed *disjoint host shards* of the global
batch (`make_array_from_process_local_data`), and run the compiled DP train
step. Asserts: identical loss on both ranks (replicated output), identical
updated params (replica lockstep — the DDP guarantee), disjoint sampler
shards, and — the reference's own correctness signal (SURVEY.md §3.5) — that
the 2-process trajectory equals a single-process run on the concatenated
global batches, across a real process boundary.
"""

import os
import pickle
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
out_path = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=world,
                           process_id=rank)
import numpy as np
from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.data.sampler import ShardedSampler
from tpu_dp.models import Net
from tpu_dp.parallel import dist
from tpu_dp.parallel.sharding import shard_batch
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

assert jax.process_count() == world and jax.process_index() == rank
mesh = dist.data_mesh()
assert mesh.shape[dist.DATA_AXIS] == world  # one device per process

ds = make_synthetic(32, 10, seed=0, name="mp")  # identical on both ranks
sampler = ShardedSampler(len(ds), num_shards=world, shard_id=rank,
                         shuffle=True, seed=7)
idx = sampler.shard_indices()

model, opt = Net(), SGD(0.9)
state = create_train_state(model, jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32), opt)
step = make_train_step(model, opt, mesh, constant_lr(0.05))

losses = []
for k in range(2):  # two steps through this rank's shard
    sel = idx[k * 8:(k + 1) * 8]
    local = {"image": normalize(ds.images[sel]), "label": ds.labels[sel]}
    batch = shard_batch(local, mesh)  # assembles the 16-example global batch
    state, metrics = step(state, batch)
    losses.append(float(metrics["loss"]))

# Params are replicated; a jitted scalar digest is identical on every
# process iff the replicas are in lockstep.
import jax.numpy as jnp
digest_fn = jax.jit(lambda p: sum(
    jnp.abs(l).sum() for l in jax.tree_util.tree_leaves(p)))
param_digest = float(digest_fn(state.params))
host_params = jax.tree_util.tree_map(np.asarray, state.params)
result = dict(rank=rank, loss=losses[-1], losses=losses,
              count=int(metrics["count"]), idx=idx.tolist(),
              param_digest=param_digest, params=host_params)
with open(out_path, "wb") as f:
    pickle.dump(result, f)
jax.distributed.shutdown()
"""


@pytest.mark.slow
def test_two_process_dp_train_step(tmp_path):
    world, port = 2, "29531"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{repo_root}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(repo_root)
    )
    procs, outs = [], []
    for rank in range(world):
        out = tmp_path / f"out{rank}.pkl"
        outs.append(out)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), str(rank), str(world), port,
                 str(out)],
                cwd=repo_root, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
        )
    logs = [p.communicate(timeout=240)[0].decode() for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-3000:]}"
    results = [pickle.loads(o.read_bytes()) for o in outs]

    # Replicated outputs agree across processes.
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], rel=1e-6)
    # Global batch count = 8 per process × 2.
    assert all(r["count"] == 16 for r in results)
    # Disjoint shards covering 32 examples.
    merged = set(results[0]["idx"]) | set(results[1]["idx"])
    assert not (set(results[0]["idx"]) & set(results[1]["idx"]))
    assert len(merged) == 32
    # Replicas hold identical updated params (lockstep).
    assert results[0]["param_digest"] == pytest.approx(
        results[1]["param_digest"], rel=1e-6
    )

    # Single-process oracle (SURVEY.md §3.5): one process, one device,
    # trained on the concatenated global batches in device order, must
    # reproduce the 2-process trajectory — the DDP-equivalence property
    # across a real process boundary, not just an in-process mesh.
    import jax

    from tpu_dp.data.cifar import make_synthetic, normalize
    from tpu_dp.models import Net
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

    ds = make_synthetic(32, 10, seed=0, name="mp")
    idx0 = np.asarray(results[0]["idx"])
    idx1 = np.asarray(results[1]["idx"])
    mesh1 = dist.data_mesh(num_devices=1)
    model, opt = Net(), SGD(0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(model, opt, mesh1, constant_lr(0.05))
    oracle_losses = []
    for k in range(2):
        sel = np.concatenate([idx0[k * 8:(k + 1) * 8], idx1[k * 8:(k + 1) * 8]])
        batch = {"image": normalize(ds.images[sel]), "label": ds.labels[sel]}
        state, metrics = step(state, batch)
        oracle_losses.append(float(metrics["loss"]))
    np.testing.assert_allclose(
        np.asarray(results[0]["losses"]), np.asarray(oracle_losses), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(results[0]["params"]),
        jax.tree_util.tree_leaves(state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow
def test_unreachable_coordinator_fails_fast(tmp_path):
    """Failure detection: a dead coordinator surfaces a contextual error
    within the timeout instead of hanging (SURVEY.md §5 — the reference's
    init_process_group has no timeout)."""
    script = tmp_path / "fail.py"
    # Note: jax's coordination client aborts the process (LOG(FATAL)) on
    # rendezvous timeout rather than raising, so "surfacing" here means a
    # bounded, diagnosable exit — not a Python exception.
    script.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from tpu_dp.parallel import dist\n"
        "dist.initialize('127.0.0.1:1', num_processes=2, process_id=1,\n"
        "                initialization_timeout=5)\n"
    )
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{repo_root}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(repo_root)
    )
    import time

    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(script)], cwd=repo_root, env=env,
        capture_output=True, timeout=120, text=True,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0  # died, did not hang
    assert elapsed < 90  # bounded by the timeout, not indefinite
    # Diagnosable: the coordination error names the failure class.
    assert "DEADLINE_EXCEEDED" in (proc.stdout + proc.stderr)
