"""Multi-process end-to-end: 2 JAX processes over a loopback coordinator.

The TPU-native analogue of the reference's `torchrun --nproc_per_node=2`
NCCL run (`cifar_example_ddp.py:55-57`'s `127.0.0.1:29500` rendezvous):
two OS processes bootstrap via `jax.distributed.initialize`, build a shared
2-device mesh (1 CPU device each), feed *disjoint host shards* of the global
batch (`make_array_from_process_local_data`), and run the compiled DP train
step. Asserts: identical loss on both ranks (replicated output), identical
updated params (replica lockstep — the DDP guarantee), disjoint sampler
shards, and — the reference's own correctness signal (SURVEY.md §3.5) — that
the 2-process trajectory equals a single-process run on the concatenated
global batches, across a real process boundary.
"""

import json
import os
import pickle
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
out_path = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
# gloo: the CPU client has no cross-process collectives by default
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=world,
                           process_id=rank)
import numpy as np
from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.data.sampler import ShardedSampler
from tpu_dp.models import Net
from tpu_dp.parallel import dist
from tpu_dp.parallel.sharding import shard_batch
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

assert jax.process_count() == world and jax.process_index() == rank
mesh = dist.data_mesh()
assert mesh.shape[dist.DATA_AXIS] == world  # one device per process

ds = make_synthetic(32, 10, seed=0, name="mp")  # identical on both ranks
sampler = ShardedSampler(len(ds), num_shards=world, shard_id=rank,
                         shuffle=True, seed=7)
idx = sampler.shard_indices()

model, opt = Net(), SGD(0.9)
state = create_train_state(model, jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32), opt)
step = make_train_step(model, opt, mesh, constant_lr(0.05))

losses = []
for k in range(2):  # two steps through this rank's shard
    sel = idx[k * 8:(k + 1) * 8]
    local = {"image": normalize(ds.images[sel]), "label": ds.labels[sel]}
    batch = shard_batch(local, mesh)  # assembles the 16-example global batch
    state, metrics = step(state, batch)
    losses.append(float(metrics["loss"]))

# Params are replicated; a jitted scalar digest is identical on every
# process iff the replicas are in lockstep.
import jax.numpy as jnp
digest_fn = jax.jit(lambda p: sum(
    jnp.abs(l).sum() for l in jax.tree_util.tree_leaves(p)))
param_digest = float(digest_fn(state.params))
host_params = jax.tree_util.tree_map(np.asarray, state.params)
result = dict(rank=rank, loss=losses[-1], losses=losses,
              count=int(metrics["count"]), idx=idx.tolist(),
              param_digest=param_digest, params=host_params)
with open(out_path, "wb") as f:
    pickle.dump(result, f)
jax.distributed.shutdown()
"""


def _free_port() -> str:
    """OS-assigned free port for the loopback coordinator — hardcoded ports
    collide across re-runs (TIME_WAIT) and concurrent pytest invocations."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def _spawn_workers(tmp_path, script_text, argv_per_rank, name, timeout=300):
    """Run one subprocess per rank; return their stdout logs.

    On timeout, every child is killed and all drained logs surface in the
    failure — a hung rank must produce diagnostics, never leaked processes
    (the coordinator blocks in `jax.distributed.initialize` when a peer
    dies early, so the first `communicate` timing out is the common case).
    """
    script = tmp_path / f"{name}.py"
    script.write_text(script_text)
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{repo_root}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(repo_root)
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), *map(str, argv)],
            cwd=repo_root, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for argv in argv_per_rank
    ]
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=timeout)[0].decode())
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
        drained = logs + [
            p.communicate()[0].decode() for p in procs[len(logs):]
        ]
        pytest.fail(
            f"{name} timed out after {timeout}s; logs:\n"
            + "\n--- next rank ---\n".join(t[-3000:] for t in drained)
        )
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"{name} failed:\n{log[-3000:]}"
    return logs


@pytest.mark.slow
def test_two_process_dp_train_step(tmp_path):
    world, port = 2, _free_port()
    outs = [tmp_path / f"out{rank}.pkl" for rank in range(world)]
    _spawn_workers(
        tmp_path, _WORKER,
        [(rank, world, port, outs[rank]) for rank in range(world)],
        name="dp_worker", timeout=240,
    )
    results = [pickle.loads(o.read_bytes()) for o in outs]

    # Replicated outputs agree across processes.
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], rel=1e-6)
    # Global batch count = 8 per process × 2.
    assert all(r["count"] == 16 for r in results)
    # Disjoint shards covering 32 examples.
    merged = set(results[0]["idx"]) | set(results[1]["idx"])
    assert not (set(results[0]["idx"]) & set(results[1]["idx"]))
    assert len(merged) == 32
    # Replicas hold identical updated params (lockstep).
    assert results[0]["param_digest"] == pytest.approx(
        results[1]["param_digest"], rel=1e-6
    )

    # Single-process oracle (SURVEY.md §3.5): one process, one device,
    # trained on the concatenated global batches in device order, must
    # reproduce the 2-process trajectory — the DDP-equivalence property
    # across a real process boundary, not just an in-process mesh.
    import jax

    from tpu_dp.data.cifar import make_synthetic, normalize
    from tpu_dp.models import Net
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

    ds = make_synthetic(32, 10, seed=0, name="mp")
    idx0 = np.asarray(results[0]["idx"])
    idx1 = np.asarray(results[1]["idx"])
    mesh1 = dist.data_mesh(num_devices=1)
    model, opt = Net(), SGD(0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(model, opt, mesh1, constant_lr(0.05))
    oracle_losses = []
    for k in range(2):
        sel = np.concatenate([idx0[k * 8:(k + 1) * 8], idx1[k * 8:(k + 1) * 8]])
        batch = {"image": normalize(ds.images[sel]), "label": ds.labels[sel]}
        state, metrics = step(state, batch)
        oracle_losses.append(float(metrics["loss"]))
    np.testing.assert_allclose(
        np.asarray(results[0]["losses"]), np.asarray(oracle_losses), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(results[0]["params"]),
        jax.tree_util.tree_leaves(state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


_RESUME_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
ckpt_dir = sys.argv[4]; phase = sys.argv[5]; out_path = sys.argv[6]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpu_dp.config import Config
from tpu_dp.train.trainer import Trainer

cfg = Config()
cfg.data.dataset = "synthetic"
cfg.data.synthetic_train_size = 64
cfg.data.synthetic_test_size = 16
cfg.data.batch_size = 8             # global batch 16 across 2 processes
cfg.train.epochs = 1
cfg.train.log_every = 100
cfg.train.eval_at_end = False
cfg.train.ckpt_dir = ckpt_dir
cfg.train.ckpt_async = False        # checkpoint durable before exit
cfg.train.resume = phase == "resume"
cfg.parallel.coordinator_address = f"127.0.0.1:{port}"
cfg.parallel.num_processes = world
cfg.parallel.process_id = rank

tr = Trainer(cfg)
if phase == "train":
    tr.fit()   # 4 steps; epoch-0 checkpoint written by process 0 only
# In the resume phase Trainer.__init__ already ran _maybe_resume: process 0
# loaded the checkpoint from disk and broadcast_one_to_all'd the TrainState
# (trainer.py) — capture exactly what each process holds at that point.
state = tr.state
leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(state)]
with open(out_path, "wb") as f:
    pickle.dump(dict(rank=rank, start_epoch=tr.start_epoch,
                     step=int(state.step),
                     leaves=[(l.dtype.str, l.tobytes()) for l in leaves]), f)
jax.distributed.shutdown()
"""


def _spawn_resume_workers(tmp_path, phase, ckpt_dir):
    port = _free_port()
    outs = [tmp_path / f"{phase}_out{rank}.pkl" for rank in range(2)]
    _spawn_workers(
        tmp_path, _RESUME_WORKER,
        [(rank, 2, port, ckpt_dir, phase, outs[rank]) for rank in range(2)],
        name=f"resume_{phase}",
    )
    return [pickle.loads(o.read_bytes()) for o in outs]


@pytest.mark.slow
def test_two_process_checkpoint_resume(tmp_path):
    """Resume across a real restart: 2 processes train and checkpoint, a
    fresh pair of processes resumes, and both hold bit-identical state.

    Exercises the one distributed code path previously untested
    (VERDICT r2 missing #4): `Trainer._maybe_resume`'s multi-process
    branch, where process 0 alone reads the checkpoint (on a pod each host
    has its own disk) and `broadcast_one_to_all`s the restored TrainState
    and epoch — the guard against the silent replica-desync failure class
    (some ranks resume, some start fresh). The reference can't do any of
    this: it saves from every rank, last writer wins, and has no load path
    (`cifar_example_ddp.py:118-119`, SURVEY.md §5 "Checkpoint / resume").
    """
    ckpt_dir = tmp_path / "ck"
    trained = _spawn_resume_workers(tmp_path, "train", ckpt_dir)
    resumed = _spawn_resume_workers(tmp_path, "resume", ckpt_dir)

    # Both fresh processes resumed at the epoch after the checkpointed one.
    assert [r["start_epoch"] for r in resumed] == [1, 1]
    # Optimizer step counter restored (4 steps ran in the train phase).
    assert resumed[0]["step"] == trained[0]["step"] == 4
    # Bit-identical restored state on BOTH ranks — params, momentum
    # buffers, and step all broadcast from process 0's checkpoint — and
    # equal to what the training run ended with.
    for a, b, t in zip(resumed[0]["leaves"], resumed[1]["leaves"],
                       trained[0]["leaves"]):
        assert a == b    # rank 0 == rank 1 (dtype + raw bytes)
        assert a == t    # resumed == end-of-training state
    # The checkpoint layout honors the proc-0-write contract: exactly the
    # single-writer manager layout — one step dir for the one epoch, the
    # atomic `latest` pointer, proc-0's metrics log, the always-on
    # flight-recorder home (`obs/`, every rank dumps on exit), and the
    # final-weights export. Any rank-suffixed duplicate or torn .tmp
    # residue (the reference's all-ranks-write-one-path mode) changes
    # this set.
    assert sorted(p.name for p in ckpt_dir.iterdir()) == [
        "final_params.msgpack", "latest", "metrics.jsonl", "obs",
        "step_0000000004",
    ]


@pytest.mark.slow
def test_unreachable_coordinator_fails_fast(tmp_path):
    """Failure detection: a dead coordinator surfaces a contextual error
    within the timeout instead of hanging (SURVEY.md §5 — the reference's
    init_process_group has no timeout)."""
    script = tmp_path / "fail.py"
    # Note: jax's coordination client aborts the process (LOG(FATAL)) on
    # rendezvous timeout rather than raising, so "surfacing" here means a
    # bounded, diagnosable exit — not a Python exception.
    script.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from tpu_dp.parallel import dist\n"
        "dist.initialize('127.0.0.1:1', num_processes=2, process_id=1,\n"
        "                initialization_timeout=5)\n"
    )
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{repo_root}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(repo_root)
    )
    import time

    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(script)], cwd=repo_root, env=env,
        capture_output=True, timeout=120, text=True,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0  # died, did not hang
    assert elapsed < 90  # bounded by the timeout, not indefinite
    # Diagnosable: the coordination error names the failure class.
    assert "DEADLINE_EXCEEDED" in (proc.stdout + proc.stderr)


_FUSED_WORKER = r"""
import os, sys
rank, world, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
# gloo: the CPU client has no cross-process collectives by default
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=world,
                           process_id=rank)
import numpy as np
import jax.numpy as jnp
from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import build_model
from tpu_dp.parallel import dist
from tpu_dp.parallel.sharding import shard_batch
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

mesh = dist.data_mesh()
model = build_model("resnet18", num_classes=10, num_filters=8,
                    dtype=jnp.bfloat16, fused_stages=(0,), fused_block_b=2)
opt = SGD(0.9)
state = create_train_state(model, jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32), opt)
step = make_train_step(model, opt, mesh, constant_lr(0.05))
ds = make_synthetic(8 * world, 10, seed=0, name="fusedmp")
lo = rank * 8
local = {"image": normalize(ds.images[lo:lo + 8]),
         "label": ds.labels[lo:lo + 8]}
state, metrics = step(state, shard_batch(local, mesh))
print("FUSEDMP_OK", rank, repr(float(metrics["loss"])), flush=True)
jax.distributed.shutdown()
"""


_RESIDENT_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
out_path = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
# gloo: the CPU client has no cross-process collectives by default
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=world,
                           process_id=rank)
import numpy as np
from tpu_dp.data.cifar import make_synthetic
from tpu_dp.data.pipeline import DataPipeline
from tpu_dp.models import Net
from tpu_dp.parallel import dist
from tpu_dp.train import SGD, constant_lr, create_train_state
from tpu_dp.train.step import make_multi_step, make_multi_step_resident

mesh = dist.data_mesh()
ds = make_synthetic(64, 10, seed=0, name="mpres")  # identical on both ranks
model, opt = Net(), SGD(0.9)

def fresh_state():
    return create_train_state(model, jax.random.PRNGKey(0),
                              np.zeros((1, 32, 32, 3), np.float32), opt)

pipe = DataPipeline(ds, batch_size=8, mesh=mesh, shuffle=True, seed=7,
                    prefetch=0)
# Resident: dataset assembled replicated from both processes, windows fed
# by process-locally assembled sharded indices.
rdata = pipe.resident_data()
rloop = make_multi_step_resident(model, opt, mesh, constant_lr(0.05),
                                 num_steps=2)
pipe.set_epoch(0)
state = fresh_state()
for n, idx in pipe.index_windows(2):   # 4 steps -> 2 windows of 2
    assert n == 2
    state, m = rloop(state, rdata, idx)
res_loss = float(m["loss"][-1])

# Streaming control: same sampler order, same body.
sloop = make_multi_step(model, opt, mesh, constant_lr(0.05), num_steps=2)
pipe.set_epoch(0)
sstate = fresh_state()
for n, item in pipe.windows(2):
    assert n == 2, "control loop expects full windows only"
    sstate, sm = sloop(sstate, item)

import jax.numpy as jnp
digest_fn = jax.jit(lambda p: sum(
    jnp.abs(l).sum() for l in jax.tree_util.tree_leaves(p)))
res_digest = float(digest_fn(state.params))
stream_digest = float(digest_fn(sstate.params))
with open(out_path, "wb") as f:
    pickle.dump(dict(rank=rank, res_loss=res_loss,
                     stream_loss=float(sm["loss"][-1]),
                     res_digest=res_digest,
                     stream_digest=stream_digest), f)
jax.distributed.shutdown()
"""


@pytest.mark.slow
def test_two_process_resident_feed(tmp_path):
    """The device-resident feed under a true multi-process mesh: replicated
    dataset assembly + process-locally assembled sharded index windows must
    reproduce the streaming trajectory exactly, with replicated outputs in
    lockstep across processes."""
    world, port = 2, _free_port()
    outs = [tmp_path / f"res{rank}.pkl" for rank in range(world)]
    _spawn_workers(
        tmp_path, _RESIDENT_WORKER,
        [(rank, world, port, outs[rank]) for rank in range(world)],
        name="resident_mp",
    )
    results = [pickle.loads(o.read_bytes()) for o in outs]
    # Resident ≡ streaming on each rank (same examples, same order).
    for r in results:
        assert r["res_loss"] == pytest.approx(r["stream_loss"], rel=1e-6)
        assert r["res_digest"] == pytest.approx(r["stream_digest"], rel=1e-6)
    # Replicated outputs agree across processes.
    assert results[0]["res_loss"] == pytest.approx(
        results[1]["res_loss"], rel=1e-6)
    assert results[0]["res_digest"] == pytest.approx(
        results[1]["res_digest"], rel=1e-6)


_HEALTH_WORKER = r"""
import sys, time
rank = int(sys.argv[1]); world = int(sys.argv[2]); run_dir = sys.argv[3]
from tpu_dp.obs.health import HeartbeatWriter
from tpu_dp.resilience.faultinject import FaultInjector

inj = FaultInjector.from_spec("", rank=rank)  # plan from TPU_DP_FAULT env
with HeartbeatWriter(run_dir, rank=rank) as hb:
    for step in range(1, 7):
        t0 = time.perf_counter()
        time.sleep(0.03)           # uniform simulated step work
        if inj is not None:
            inj.on_step(step)      # the injected straggler delay
        hb.beat(step, (time.perf_counter() - t0) * 1e3)
print("HEALTH_OK", rank, flush=True)
"""


@pytest.mark.obs
def test_two_process_straggler_and_hang_detection(tmp_path, monkeypatch):
    """Cross-rank straggler attribution over a real process boundary: two
    OS processes heartbeat into a shared run dir; the deterministic fault
    injector (`TPU_DP_FAULT` delay, the same spec production uses) slows
    rank 1 at step 3 only. The monitor must name exactly that rank and
    step with the measured lag factor — and a stale-heartbeat check on the
    same files must flag a hang per the configured ``on_flag``."""
    import time

    from tpu_dp.obs.health import HealthError, HealthMonitor

    monkeypatch.setenv("TPU_DP_FAULT", "delay:step=3,rank=1,ms=300")
    run_dir = tmp_path / "obs"
    logs = _spawn_workers(
        tmp_path, _HEALTH_WORKER,
        [(rank, 2, run_dir) for rank in range(2)],
        name="health_mp", timeout=120,
    )
    assert all("HEALTH_OK" in log for log in logs)

    mon = HealthMonitor(run_dir, world=2, straggler_factor=3.0,
                        stale_after_s=3600.0)
    stragglers = [i for i in mon.scan() if i.kind == "straggler"]
    assert stragglers, "injected delay not flagged"
    # The worst offender is the injected-delay rank at the injected step.
    worst = max(stragglers, key=lambda i: i.ratio)
    assert (worst.rank, worst.step) == (1, 3)
    assert worst.ratio >= 3.0          # the measured lag factor
    assert worst.step_ms >= 300.0      # carries the delay
    # Latest beats are healthy — the live check stays quiet…
    assert mon.check(now=time.time()) == []

    # …until the heartbeats go stale (simulated hang): warn mode reports,
    # raise mode aborts with the flagged ranks attached.
    later = time.time() + 10.0
    lax = HealthMonitor(run_dir, world=2, stale_after_s=5.0,
                        logger=(logged := []).append)
    issues = lax.report(lax.check(now=later))
    assert {i.rank for i in issues} == {0, 1}
    assert all(i.kind == "stale" for i in issues) and len(logged) == 2
    strict = HealthMonitor(run_dir, world=2, stale_after_s=5.0,
                           on_flag="raise")
    with pytest.raises(HealthError):
        strict.report(strict.check(now=later))


_ELASTIC_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
ckpt = sys.argv[4]; out_path = sys.argv[5]; fault = sys.argv[6]
update_sharding = sys.argv[7]; train_size = int(sys.argv[8])
guard = len(sys.argv) > 9 and sys.argv[9] == "guard"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpu_dp.config import Config
from tpu_dp.train.trainer import Trainer
from tpu_dp.resilience import PreemptedError

cfg = Config()
cfg.data.dataset = "synthetic"
cfg.data.synthetic_train_size = train_size
cfg.data.synthetic_test_size = 16
cfg.data.batch_size = 4            # per process: global batch 12 -> 8
cfg.train.epochs = 2
cfg.train.log_every = 100
cfg.train.eval_at_end = False
cfg.train.steps_per_call = 1
cfg.train.ckpt_dir = ckpt
cfg.train.ckpt_async = False
cfg.train.obs = "basic"
cfg.train.update_sharding = update_sharding
cfg.resilience.elastic = True
cfg.resilience.fault = fault
cfg.resilience.regroup_timeout_s = 60
cfg.parallel.coordinator_address = f"127.0.0.1:{port}"
cfg.parallel.num_processes = world
cfg.parallel.process_id = rank
if guard:
    # Guardrail twin of the elastic run: per-step snapshots give the SDC
    # rollback a trusted pre-corruption resume point, the per-step audit
    # bounds detection latency to one boundary, and spike detection stays
    # unarmed (min_steps > run length) so only the audit drives events.
    cfg.guard.enabled = True
    cfg.guard.action = "skip"
    cfg.guard.sdc_every_steps = 1
    cfg.guard.spike_min_steps = 64
    cfg.resilience.snapshot_every_steps = 1

from tpu_dp.train.trainer import run_elastic
try:
    # run_elastic == Trainer(cfg).fit() everywhere except a fired
    # `relaunch:` fault, which rejoins the run in-process (the
    # deterministic twin of "the preempted rank comes back").
    tr, result = run_elastic(cfg)
except PreemptedError as e:
    print("ELASTIC_LEFT", rank, repr(str(e)), flush=True)
    sys.exit(143)
from tpu_dp.obs.counters import counters
host_params = jax.tree_util.tree_map(np.asarray, tr.state.params)
with open(out_path, "wb") as f:
    pickle.dump(dict(
        rank=rank, sid=tr.stable_rank, new_rank=tr.ctx.process_index,
        world=tr.ctx.process_count, params=host_params,
        record=tr.elastic.record.to_json(), counters=counters.snapshot(),
        history=result["history"], step=int(tr.state.step),
    ), f)
print("ELASTIC_OK", rank, flush=True)
sys.exit(0)
"""


def _elastic_oracle_params(record: dict, *, world0=3, num_examples,
                           batch=4, epochs=2, seed=0, sampler_seed=0):
    """Single-device oracle of the elastic run's exact batch sequence.

    Reconstructs, from the published membership record alone, every global
    batch the 3-then-2-rank run consumed — `ShardedSampler` streams for
    the pre-regroup segments, `elastic_resplit` for the re-split tail —
    and trains the same model on them one step at a time. Matching final
    params prove the trainer consumed exactly the predicted samples in
    exactly the predicted order across the world change (the
    DDP-equivalence oracle of `test_two_process_dp_train_step`, extended
    over a membership transition).
    """
    import jax

    from tpu_dp.config import Config
    from tpu_dp.data.cifar import load_dataset
    from tpu_dp.data.sampler import ShardedSampler, elastic_resplit
    from tpu_dp.models import Net
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, create_train_state, make_train_step
    from tpu_dp.train.schedule import make_schedule

    defaults = Config()
    resume = record["resume"]
    interrupted, lineage = int(resume["epoch"]), resume["lineage"]
    world1 = int(record["world"])
    ds = load_dataset("synthetic", "./data", train=True,
                      allow_synthetic=True,
                      synthetic_num_examples=num_examples, seed=seed)

    def segment_streams(epoch, prior, world):
        if not prior:
            out = []
            for r in range(world):
                s = ShardedSampler(len(ds), world, r, shuffle=True,
                                   seed=sampler_seed)
                s.set_epoch(epoch)
                out.append(s.shard_indices())
            return out
        return [elastic_resplit(len(ds), True, sampler_seed, epoch, batch,
                                prior, world, r) for r in range(world)]

    mesh1 = dist.data_mesh(num_devices=1)
    model, opt = Net(), SGD(defaults.optim.momentum)
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    step = make_train_step(model, opt, mesh1, make_schedule(
        "constant", defaults.optim.lr, 1, 0, 0.0))
    consumed_counts = np.zeros(len(ds), np.int64)
    for epoch in range(epochs):
        if epoch < interrupted:
            segments = [([], world0, None)]
        elif epoch == interrupted:
            segments = [([], world0, int(lineage[0][1])),
                        (lineage, world1, None)]
        else:
            segments = [([], world1, None)]
        for prior, world, steps in segments:
            streams = segment_streams(epoch, prior, world)
            n = (min(len(s) for s in streams) // batch
                 if steps is None else steps)
            for k in range(n):
                sel = np.concatenate(
                    [s[k * batch:(k + 1) * batch] for s in streams])
                consumed_counts[np.asarray(sel)] += 1
                state, _ = step(state, {"image": ds.images[sel],
                                        "label": ds.labels[sel]})
    return state, consumed_counts


def _run_elastic_workers(tmp_path, fault, update_sharding="replicated",
                         train_size=48, guard=False):
    port = _free_port()
    outs = [tmp_path / f"el{rank}.pkl" for rank in range(3)]
    script = tmp_path / "elastic_worker.py"
    script.write_text(_ELASTIC_WORKER)
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{repo_root}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(repo_root)
    )
    env.pop("TPU_DP_FAULT", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(rank), "3", port,
             str(tmp_path / "ck"), str(outs[rank]), fault, update_sharding,
             str(train_size)] + (["guard"] if guard else []),
            cwd=repo_root, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for rank in range(3)
    ]
    return procs, outs


def _assert_elastic_outcome(procs, outs, victim=2):
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=240)[0].decode())
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
        drained = logs + [
            p.communicate()[0].decode() for p in procs[len(logs):]
        ]
        pytest.fail(
            "elastic workers timed out; logs:\n"
            + "\n--- next rank ---\n".join(t[-3000:] for t in drained)
        )
    # The preempted rank exits 143 (terminated-by-request); the survivors
    # finish the job with exit 0 and NO operator action.
    for rank, (p, log) in enumerate(zip(procs, logs)):
        want = 143 if rank == victim else 0
        assert p.returncode == want, (
            f"rank {rank}: rc {p.returncode} != {want}\n{log[-3000:]}"
        )
    assert f"ELASTIC_LEFT {victim}" in logs[victim]
    results = {}
    for rank, out in enumerate(outs):
        if rank != victim:
            results[rank] = pickle.loads(out.read_bytes())
    return results, logs


def _assert_elastic_run(results, victim=2, num_examples=48):
    """The shared elastic acceptance block (record, coverage, oracle)."""
    import jax

    survivors = sorted(results)
    a = results[survivors[0]]
    record = a["record"]
    # Membership epoch 1: survivors only, the victim attributed departed.
    assert record["epoch"] == 1
    assert record["members"] == survivors
    assert [d["sid"] for d in record["departed"]] == [victim]
    assert a["world"] == 2
    # Dense ranks reassigned in stable-id order.
    for sid, r in zip(survivors, range(2)):
        assert results[sid]["new_rank"] == r
    # The regroup is attributed in the obs counters.
    for sid in survivors:
        c = results[sid]["counters"]
        assert c["elastic.regroups"] == 1
        assert c["elastic.lost_ranks"] == 1
        assert c["elastic.regroup_s"] > 0
        assert c["elastic.membership_epoch"] == 1
    # Survivors hold bit-identical params (replica lockstep survived the
    # reshard)...
    for x, y in zip(jax.tree_util.tree_leaves(a["params"]),
                    jax.tree_util.tree_leaves(
                        results[survivors[1]]["params"])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # ... equal to the single-device oracle built from the membership
    # record alone — proving the exact post-regroup sample schedule.
    oracle_state, counts = _elastic_oracle_params(
        record, num_examples=num_examples)
    for x, y in zip(jax.tree_util.tree_leaves(a["params"]),
                    jax.tree_util.tree_leaves(oracle_state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=2e-5)
    # Exactly-once coverage: in the interrupted epoch every sample was
    # consumed once, except up to one seam batch (< new-world × batch)
    # shed by the same drop_remainder policy every epoch end applies.
    total_epochs = 2
    dropped = int((counts < total_epochs).sum())
    assert dropped < 2 * 4 * 2, f"{dropped} samples dropped"
    assert (counts <= total_epochs).all(), "a sample was consumed twice"
    return record


@pytest.mark.slow
@pytest.mark.elastic
def test_three_process_elastic_preempt_rank2(tmp_path):
    """The elastic acceptance run (ISSUE 7): 3 CPU processes, rank 2 gets
    a (self-delivered, deterministic) SIGTERM at step 2 via
    ``TPU_DP_FAULT=preempt:`` — the survivors quiesce at a common step,
    snapshot, re-`initialize` at world 2, reshard, re-split the epoch,
    re-verify the DP304 fingerprint, and finish BOTH epochs with final
    params matching the single-device oracle of the exact predicted
    sample schedule."""
    procs, outs = _run_elastic_workers(tmp_path, "preempt:step=2,rank=2")
    results, logs = _assert_elastic_outcome(procs, outs, victim=2)
    record = _assert_elastic_run(results, victim=2)
    assert record["reason"] == "graceful"
    # DP304 re-verification ran on the shrunk mesh before the first
    # post-regroup step (logged by the new rank 0; the check itself is an
    # allgather-compare on every rank). The tag is keyed by membership
    # epoch AND world size (ISSUE 12 satellite).
    new_rank0 = next(s for s in results if results[s]["new_rank"] == 0)
    assert ("collective-schedule fingerprint (train_step@me1w2)"
            in logs[new_rank0])


@pytest.mark.slow
@pytest.mark.elastic
def test_three_process_elastic_external_sigterm_rank0(tmp_path):
    """Same protocol under a REAL external SIGTERM, aimed at rank 0 — the
    hardest seat: the membership leader, the snapshot writer, and the
    metrics owner all hand over. The kill lands at an arbitrary step
    (driver waits for training to be underway via the heartbeat file),
    and the oracle is reconstructed from whatever stop step the protocol
    agreed on. The sharded weight update rides along, so the regroup
    reshards real cross-process optimizer state."""
    import signal
    import time

    # The one-shot delay parks rank 0 for 3s at its step-2 boundary — a
    # deterministic window for the EXTERNAL signal to land mid-training
    # (the run is otherwise milliseconds per step; an unpinned kill races
    # past the end of the job and the leaver legitimately finishes).
    procs, outs = _run_elastic_workers(
        tmp_path, "delay:step=2,rank=0,ms=3000",
        update_sharding="sharded", train_size=96)
    hb = tmp_path / "ck" / "obs" / "heartbeat_r00000.jsonl"
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if hb.exists() and hb.read_text().count("\n") >= 1:
            break
        if any(p.poll() is not None for p in procs):
            break  # a worker died early; the outcome assert will report
        time.sleep(0.05)
    procs[0].send_signal(signal.SIGTERM)
    results, logs = _assert_elastic_outcome(procs, outs, victim=0)
    record = _assert_elastic_run(results, victim=0, num_examples=96)
    assert record["reason"] == "graceful"
    # The demoted-into-oblivion rank 0's successor owns rank-0 duties:
    # the post-regroup metrics records carry the new membership epoch.
    metrics = [json.loads(line) for line in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    regroups = [m for m in metrics if m.get("event") == "elastic_regroup"]
    assert len(regroups) == 1
    assert regroups[0]["membership_epoch"] == 1
    assert regroups[0]["world"] == 2
    assert [m["membership_epoch"] for m in metrics
            if "epoch" in m and m.get("membership_epoch") == 1]


@pytest.mark.slow
@pytest.mark.elastic
@pytest.mark.guard
def test_three_process_sdc_audit_names_rank2_and_regroups(tmp_path):
    """The guardrail SDC acceptance run (ISSUE 8): 3 CPU processes, a
    deterministic single-bit param flip on rank 2 at step 2
    (``TPU_DP_FAULT=sdc:step=2,rank=2``). The per-boundary cross-replica
    audit catches the divergence at the next boundary and NAMES rank 2
    (majority vote over the bit-checksums, down to the leaf); rank 2
    hands itself to the membership ledger (leave + rollback flavor) and
    exits 143, the survivors regroup to world 2, resume from the newest
    snapshot that PREDATES the corruption (post-detection snapshots are
    suppressed, pre-detection ones quarantine-marked), and finish both
    epochs matching the single-device oracle — corruption detected,
    attributed, evicted, and rewound away with zero operator action."""
    procs, outs = _run_elastic_workers(
        tmp_path, "sdc:step=2,rank=2", train_size=96, guard=True)
    results, logs = _assert_elastic_outcome(procs, outs, victim=2)
    record = _assert_elastic_run(results, victim=2, num_examples=96)
    # Rollback regroup (never graceful: a graceful final snapshot would
    # persist the corrupt state), resumed at or before the flip step.
    assert record["reason"] == "rollback"
    assert record["resume"]["lineage"][0][1] <= 2
    # The audit named rank 2 (the attribution line is rank-0-gated; every
    # rank's detection is asserted via its counters below).
    assert any("suspect rank(s) [2]" in log for log in logs)
    # ... and the survivors' counters carry the audit trail.
    for sid in sorted(results):
        c = results[sid]["counters"]
        assert c["guard.sdc_mismatches"] >= 1
        assert c["guard.sdc_audits"] >= 1
    # The eviction is attributed in the membership record's suspect reason.
    assert any("sdc" in d.get("reason", "").lower()
               for d in record["departed"])
    # The quarantine ledger holds the finding with rank attribution.
    recs = [json.loads(line) for line in
            (tmp_path / "ck" / "quarantine.jsonl").read_text().splitlines()]
    sdc = [r for r in recs if r["kind"] == "sdc"]
    assert sdc and sdc[0]["suspects"] == [2]
    assert sdc[0]["leaves"]["2"]  # leaf-level attribution present
    # The guard_sdc event reached the metrics stream too.
    metrics = [json.loads(line) for line in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    ev = [m for m in metrics if m.get("event") == "guard_sdc"]
    assert ev and ev[0]["suspects"] == [2]

    # --- ISSUE 9 acceptance: black boxes + the obsctl timeline ---------
    # Every rank left a flight-recorder dump — the evicted rank's exit
    # path (PreemptedError, 143) AND the survivors' clean completions.
    from tpu_dp.obs import flightrec, obsctl

    ck = tmp_path / "ck"
    dumps = {}
    for d in sorted((ck / "obs").glob("flightrec_r*.json")):
        payload = flightrec.read_dump(d)
        dumps[payload["rank"]] = payload
    assert sorted(dumps) == [0, 1, 2], "a rank left no black box"
    assert "PreemptedError" in dumps[2]["reason"]
    assert all(dumps[r]["reason"] == "clean" for r in (0, 1))
    assert any(e["kind"] == "guard_evict" for e in dumps[2]["events"])

    # `obsctl timeline` over NOTHING but the artifacts directory
    # reconstructs the ordered story: divergence detected -> rank
    # attributed -> eviction -> rollback resume -> completion.
    out = obsctl.build_timeline(obsctl.RunArtifacts(ck),
                                include_steps=True)
    events = out["events"]
    assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)
    kinds = [e["kind"] for e in events]
    story = ["guard_sdc", "eviction", "elastic_regroup", "epoch_complete"]
    positions = [kinds.index(k) for k in story]
    assert positions == sorted(positions), (
        f"story out of order: {list(zip(story, positions))}"
    )
    sdc_ev = events[kinds.index("guard_sdc")]
    assert sdc_ev["detail"]["suspects"] == [2]  # rank attributed
    evict = next(e for e in events if e["kind"] == "eviction")
    assert evict["rank"] == 2 and "sdc" in evict["detail"]["reason"]
    regroup = next(e for e in events if e["kind"] == "elastic_regroup")
    assert regroup["detail"]["flavor"] == "rollback"  # rollback resume
    exits = [e for e in events if e["kind"] == "exit"]
    assert sum(1 for e in exits
               if e["detail"]["reason"] == "clean") == 2  # completion
    # No duplicate replayed-step events: the post-eviction world replayed
    # steps past the rollback point, yet each optimizer step appears
    # exactly once (the surviving membership-epoch attempt wins).
    steps = [e["step"] for e in events if e["kind"] == "step"]
    assert steps and len(steps) == len(set(steps))
    assert out["stats"]["steps"]["replayed_beats_deduped"] > 0


def _read_ledger_records(ckpt_dir: Path) -> list[dict]:
    """All membership-epoch records of the run's (single) generation."""
    gens = sorted((ckpt_dir / "membership").iterdir())
    assert len(gens) == 1, gens
    return [json.loads(p.read_text())
            for p in sorted(gens[0].glob("epoch_*.json"))]


def _elastic_ledger_oracle_params(records, *, num_examples, batch=4,
                                  epochs=2, seed=0, sampler_seed=0):
    """Single-device oracle over an ARBITRARY graceful/grow transition
    history, reconstructed from the membership ledger alone.

    Generalizes `_elastic_oracle_params` (one shrink) to any sequence of
    graceful shrinks and grows: for each dataset epoch, the newest record
    whose resume targets it supplies the full consumption lineage (each
    prefix is a segment: ``steps_i`` optimizer steps at ``world_i``), the
    remainder runs re-split at that record's world; epochs no transition
    touched run wholly at the world current when they started. Rollback
    flavors rewind the clock and are out of scope here (asserted absent).
    """
    import jax

    from tpu_dp.config import Config
    from tpu_dp.data.cifar import load_dataset
    from tpu_dp.data.sampler import ShardedSampler, elastic_resplit
    from tpu_dp.models import Net
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, create_train_state, make_train_step
    from tpu_dp.train.schedule import make_schedule

    assert all(r.get("reason") in ("initial", "graceful", "grow")
               for r in records), [r.get("reason") for r in records]
    defaults = Config()
    ds = load_dataset("synthetic", "./data", train=True,
                      allow_synthetic=True,
                      synthetic_num_examples=num_examples, seed=seed)

    def segment_streams(epoch, prior, world):
        if not prior:
            out = []
            for r in range(world):
                s = ShardedSampler(len(ds), world, r, shuffle=True,
                                   seed=sampler_seed)
                s.set_epoch(epoch)
                out.append(s.shard_indices())
            return out
        return [elastic_resplit(len(ds), True, sampler_seed, epoch, batch,
                                prior, world, r) for r in range(world)]

    def segments_for_epoch(e):
        touching = [r for r in records[1:]
                    if (r.get("resume") or {}).get("epoch") == e]
        if touching:
            last = touching[-1]
            lineage = [list(map(int, seg))
                       for seg in last["resume"]["lineage"]]
            segs = []
            for i, (world, steps) in enumerate(lineage):
                segs.append((lineage[:i], world, steps))
            segs.append((lineage, int(last["world"]), None))
            return segs
        # Untouched epoch: the world current when it started = the newest
        # record whose transition predates it (resume.epoch < e).
        world = int(records[0]["world"])
        for r in records[1:]:
            if (r.get("resume") or {}).get("epoch", 10**9) < e:
                world = int(r["world"])
        return [([], world, None)]

    mesh1 = dist.data_mesh(num_devices=1)
    model, opt = Net(), SGD(defaults.optim.momentum)
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    step = make_train_step(model, opt, mesh1, make_schedule(
        "constant", defaults.optim.lr, 1, 0, 0.0))
    consumed_counts = np.zeros(len(ds), np.int64)
    for epoch in range(epochs):
        for prior, world, steps in segments_for_epoch(epoch):
            streams = segment_streams(epoch, prior, world)
            n = (min(len(s) for s in streams) // batch
                 if steps is None else steps)
            for k in range(n):
                sel = np.concatenate(
                    [s[k * batch:(k + 1) * batch] for s in streams])
                consumed_counts[np.asarray(sel)] += 1
                state, _ = step(state, {"image": ds.images[sel],
                                        "label": ds.labels[sel]})
    return state, consumed_counts


@pytest.mark.slow
@pytest.mark.elastic
def test_three_process_elastic_grow_relaunch_rank2(tmp_path):
    """The grow acceptance run (ISSUE 12): 3 CPU processes, rank 2
    departs at step 2 via the ``relaunch:`` fault (the deterministic
    in-process twin of a preemption), the survivors shrink to world 2 —
    and then rank 2 COMES BACK: it discovers the live run through the
    membership ledger, publishes a fenced join request, the members run a
    grow-flavor quiesce, and the mesh regrows to world 3, resharding real
    cross-process flat-sharded optimizer state upward. All three ranks
    finish BOTH epochs, hold bitwise-identical params, and match the
    single-device oracle of the exact 3→2→3 sample schedule reconstructed
    from the ledger alone — elasticity as capacity tracking availability,
    not monotone decay."""
    import jax

    procs, outs = _run_elastic_workers(
        tmp_path, "relaunch:step=2,rank=2",
        update_sharding="sharded", train_size=96)
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=300)[0].decode())
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
        drained = logs + [
            p.communicate()[0].decode() for p in procs[len(logs):]
        ]
        pytest.fail(
            "grow workers timed out; logs:\n"
            + "\n--- next rank ---\n".join(t[-4000:] for t in drained)
        )
    # EVERY rank exits 0: the departed rank rejoined and completed.
    for rank, (p, log) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, (
            f"rank {rank}: rc {p.returncode}\n{log[-4000:]}"
        )
    results = {r: pickle.loads(outs[r].read_bytes()) for r in range(3)}

    # World regrew: every rank reports world 3 at the final epoch.
    assert [results[r]["world"] for r in range(3)] == [3, 3, 3]
    final = results[0]["record"]
    assert final["members"] == [0, 1, 2]
    assert final["reason"] == "grow"
    assert [j["sid"] for j in final["joined"]] == [2]
    # The service stayed pinned to the incumbent leader.
    assert final["service_sid"] == 0

    # Ledger story: 3 → 2 (graceful departure) → 3 (grow).
    records = _read_ledger_records(tmp_path / "ck")
    assert [r["world"] for r in records] == [3, 2, 3]
    assert records[1]["reason"] == "graceful"
    assert [d["sid"] for d in records[1]["departed"]] == [2]
    assert records[2]["reason"] == "grow"

    # Counters: survivors saw both transitions; the rejoiner counts its
    # departure AND its join (process-global registry spans incarnations).
    for sid in (0, 1):
        c = results[sid]["counters"]
        assert c["elastic.regroups"] == 2
        assert c["elastic.lost_ranks"] == 1
        assert c["elastic.joined_ranks"] == 1
        assert c["elastic.membership_epoch"] == 2
    c2 = results[2]["counters"]
    assert c2["elastic.departures"] == 1
    assert c2["elastic.joins"] == 1

    # All three ranks hold bitwise-identical params (lockstep survived
    # shrink-reshard AND grow-reshard of the flat-sharded opt state)...
    for r in (1, 2):
        for x, y in zip(jax.tree_util.tree_leaves(results[0]["params"]),
                        jax.tree_util.tree_leaves(results[r]["params"])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # ... equal to the ledger-reconstructed single-device oracle.
    oracle_state, counts = _elastic_ledger_oracle_params(
        records, num_examples=96)
    for x, y in zip(jax.tree_util.tree_leaves(results[0]["params"]),
                    jax.tree_util.tree_leaves(oracle_state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=2e-5)
    # Exactly-once across the union of shrink AND grow segments: nothing
    # consumed twice; seam shedding bounded by one global batch per
    # re-split (two re-splits happened).
    assert (counts <= 2).all(), "a sample was consumed twice in one epoch"
    dropped = int((counts < 2).sum())
    assert dropped < 2 * 3 * 4 * 2, f"{dropped} samples dropped"

    # DP304 re-verified on BOTH re-formed meshes, world-keyed tags.
    joined_logs = "\n".join(logs)
    assert "collective-schedule fingerprint (train_step@me1w2)" in joined_logs
    assert "collective-schedule fingerprint (train_step@me2w3)" in joined_logs

    # The obsctl timeline, from artifacts alone, tells
    # departure → shrink-regroup → join → grow-regroup → completion.
    from tpu_dp.obs import obsctl

    out = obsctl.build_timeline(obsctl.RunArtifacts(tmp_path / "ck"))
    kinds = [e["kind"] for e in out["events"]]
    story = ["elastic_departure", "elastic_regroup", "rank_joined",
             "elastic_grow"]
    positions = [kinds.index(k) for k in story]
    # The run's FINAL completion comes after the whole round trip (an
    # intermediate epoch may legitimately complete before the grow lands).
    positions.append(len(kinds) - 1 - kinds[::-1].index("epoch_complete"))
    story.append("epoch_complete(last)")
    assert positions == sorted(positions), (
        f"story out of order: {list(zip(story, positions))}"
    )
    grow_ev = next(e for e in out["events"] if e["kind"] == "elastic_grow")
    assert grow_ev["detail"]["world"] == 3
    joined_ev = next(e for e in out["events"] if e["kind"] == "rank_joined")
    assert joined_ev.get("rank") == 2 or (
        joined_ev.get("detail", {}).get("sid") == 2)


@pytest.mark.slow
@pytest.mark.elastic
def test_two_process_joiner_crash_mid_handshake_no_wedge(tmp_path):
    """A joiner that dies mid-handshake must cost the incumbents only the
    bounded bootstrap timeout (ISSUE 12 acceptance): 2 processes train,
    the driver forges a valid join request for sid 2 and never shows up —
    the members quiesce, publish the grow plan, admit, time out waiting
    for the ghost at the coordination connect, and RE-FORM at world 2
    from the very snapshot the grow quiesce committed (no wedge, no
    rollback, both epochs complete)."""
    import time

    port = _free_port()
    outs = [tmp_path / f"jc{rank}.pkl" for rank in range(2)]
    script = tmp_path / "jc_worker.py"
    script.write_text(_JOINER_CRASH_WORKER)
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{repo_root}{os.pathsep}{env['PYTHONPATH']}"
        if env.get("PYTHONPATH") else str(repo_root)
    )
    env.pop("TPU_DP_FAULT", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(rank), port,
             str(tmp_path / "ck"), str(outs[rank])],
            cwd=repo_root, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for rank in range(2)
    ]
    # Wait for training to be underway (the delay: fault pins rank 0 at
    # its step-2 boundary for 3s — a deterministic window), then forge
    # the ghost joiner's request into the live generation.
    mem_root = tmp_path / "ck" / "membership"
    deadline = time.monotonic() + 120
    gen_dir = None
    while time.monotonic() < deadline:
        gens = sorted(mem_root.iterdir()) if mem_root.exists() else []
        if gens and (gens[0] / "epoch_0000.json").exists():
            gen_dir = gens[0]
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.05)
    assert gen_dir is not None, "no membership generation appeared"
    from tpu_dp.resilience.elastic import MembershipLedger

    ghost = MembershipLedger(gen_dir, 2)
    assert ghost.publish_join(1, 2, token="ghost-token",
                              generation=gen_dir.name)
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=300)[0].decode())
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
        drained = logs + [
            p.communicate()[0].decode() for p in procs[len(logs):]
        ]
        pytest.fail(
            "joiner-crash workers timed out (wedged?); logs:\n"
            + "\n--- next rank ---\n".join(t[-4000:] for t in drained)
        )
    for rank, (p, log) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, (
            f"rank {rank}: rc {p.returncode}\n{log[-4000:]}"
        )
    results = {r: pickle.loads(outs[r].read_bytes()) for r in range(2)}
    # The incumbents ended at world 2 — grow attempted, aborted, no loss.
    assert [results[r]["world"] for r in range(2)] == [2, 2]
    records = _read_ledger_records(tmp_path / "ck")
    # epoch 1 admitted the ghost (world 3), epoch 2 is the corrective
    # re-form at world 2 with the handshake-timeout attribution.
    assert [r["world"] for r in records] == [2, 3, 2]
    assert records[1]["reason"] == "grow"
    assert [j["sid"] for j in records[1]["joined"]] == [2]
    assert records[2]["reason"] == "grow_aborted"
    assert records[2]["departed"][0]["sid"] == 2
    assert "handshake timeout" in records[2]["departed"][0]["reason"]
    # Same resume payload on both: the aborted grow lost no work.
    assert records[2]["resume"] == records[1]["resume"]
    # Params stayed in lockstep through the abort.
    import jax

    for x, y in zip(jax.tree_util.tree_leaves(results[0]["params"]),
                    jax.tree_util.tree_leaves(results[1]["params"])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


_JOINER_CRASH_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
out_path = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpu_dp.config import Config
from tpu_dp.train.trainer import run_elastic

cfg = Config()
cfg.data.dataset = "synthetic"
cfg.data.synthetic_train_size = 64
cfg.data.synthetic_test_size = 16
cfg.data.batch_size = 4
cfg.train.epochs = 2
cfg.train.log_every = 100
cfg.train.eval_at_end = False
cfg.train.steps_per_call = 1
cfg.train.ckpt_dir = ckpt
cfg.train.ckpt_async = False
cfg.train.obs = "basic"
cfg.resilience.elastic = True
# Short bound: the ghost joiner never connects; the grow bootstrap must
# fail within this and fall back to world 2.
cfg.resilience.regroup_timeout_s = 8
# One-shot delay pins rank 0 at its step-2 boundary for 3s so the driver
# can forge the ghost join while training is underway.
cfg.resilience.fault = "delay:step=2,rank=0,ms=3000"
cfg.parallel.coordinator_address = f"127.0.0.1:{port}"
cfg.parallel.num_processes = 2
cfg.parallel.process_id = rank

tr, result = run_elastic(cfg)
from tpu_dp.obs.counters import counters
host_params = jax.tree_util.tree_map(np.asarray, tr.state.params)
with open(out_path, "wb") as f:
    pickle.dump(dict(rank=rank, world=tr.ctx.process_count,
                     record=tr.elastic.record.to_json(),
                     params=host_params,
                     counters=counters.snapshot()), f)
print("JOINER_CRASH_OK", rank, flush=True)
sys.exit(0)
"""


@pytest.mark.slow
def test_two_process_fused_conv_step(tmp_path):
    """The fused Pallas-conv model under a true multi-process mesh: the
    custom-partitioned kernel must compose with the process-local input
    assembly (`make_array_from_process_local_data`), and the replicated
    loss must agree bitwise across processes and match a single-process
    run of the same global batch."""
    port = _free_port()
    logs = _spawn_workers(
        tmp_path, _FUSED_WORKER,
        [(rank, 2, port) for rank in range(2)],
        name="fused_mp",
    )
    losses = []
    for log in logs:
        for line in log.splitlines():
            if line.startswith("FUSEDMP_OK"):
                losses.append(float(line.split()[2]))
    assert len(losses) == 2, f"missing OK lines:\n{logs}"
    assert losses[0] == losses[1], losses

    # Single-process oracle on the concatenated global batch.
    import jax
    import jax.numpy as jnp

    from tpu_dp.data.cifar import make_synthetic, normalize
    from tpu_dp.models import build_model
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

    mesh = dist.data_mesh(devices=jax.devices()[:1])
    model = build_model("resnet18", num_classes=10, num_filters=8,
                        dtype=jnp.bfloat16, fused_stages=(0,), fused_block_b=2)
    opt = SGD(0.9)
    state = create_train_state(model, jax.random.PRNGKey(0),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    step = make_train_step(model, opt, mesh, constant_lr(0.05))
    ds = make_synthetic(16, 10, seed=0, name="fusedmp")
    _, metrics = step(state, {"image": normalize(ds.images),
                              "label": ds.labels})
    assert losses[0] == pytest.approx(float(metrics["loss"]), rel=2e-5)
