"""Level-3 dplint (`tpu_dp.analysis.hlo` + `recompile`) — the compiled
artifact.

What levels 1–2 cannot see is exactly what this file proves:

1. The *shipped* step programs compile to the artifact the paper's
   DDP-parity claim rests on — one combinable gradient all-reduce group
   plus the two metric reductions, no all-gathers, every donated buffer
   aliased (DP303's "shipped steps are proven aliased" half).
2. The collective-schedule fingerprint is deterministic (same program →
   same digest; different program → different digest) and the cross-rank
   startup hook accepts/validates digests.
3. Dropped donation is demonstrably caught: a program whose donated buffer
   cannot alias (dtype change) fails DP303.
4. The RecompileGuard counts real post-warmup retraces and only those.

Fast lane: ``pytest -m analysis``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from tpu_dp.analysis import hlo, recompile
from tpu_dp.analysis.recompile import RecompileError, RecompileGuard

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. the shipped compiled artifact ------------------------------------

@pytest.fixture(scope="module")
def repo_hlo():
    findings, artifact = hlo.verify_repo_hlo(accum_steps=(1,), world=8)
    return findings, artifact


def test_shipped_steps_compile_clean(repo_hlo):
    findings, _ = repo_hlo
    assert findings == []


def test_shipped_train_steps_are_proven_aliased(repo_hlo):
    """Every donated buffer of every train-step program survives as a real
    input_output_alias entry in the compiled module — donation was not
    silently dropped (DP303's positive half)."""
    _, artifact = repo_hlo
    train_programs = {k: v for k, v in artifact["programs"].items()
                      if k != "eval_step"}
    assert train_programs
    for name, rec in train_programs.items():
        assert rec["donated_inputs"] > 0, name
        assert rec["aliased_inputs"] == rec["donated_inputs"], (
            f"{name}: {rec['aliased_inputs']}/{rec['donated_inputs']} "
            f"donated buffers aliased"
        )


def test_shipped_steps_have_one_combinable_gradient_group(repo_hlo):
    """Replicated-mode train-step modules contain only all-reduces: a
    single combinable gradient group (full-mesh replica groups, add) plus
    the two metric scalars — no all-gather/reduce-scatter/permute
    anywhere. (Serve programs have their own schedule contract —
    `test_serve_programs_in_artifact`.)"""
    _, artifact = repo_hlo
    checked = 0
    for name, rec in artifact["programs"].items():
        if rec["update_sharding"] != "replicated" \
                or name.startswith("serve_step"):
            continue
        checked += 1
        assert set(rec["counts"]) <= {"all-reduce"}, (name, rec["counts"])
        groups = {op["replica_groups"] for op in rec["collectives"]}
        assert len(groups) <= 1, (name, groups)
        if name != "eval_step":
            assert rec["grad_reduce_ops"] >= 1, name
        assert rec["metric_allreduce_ops"] == 2, (name, rec)
    assert checked >= 3


def test_serve_programs_in_artifact(repo_hlo):
    """The serving forwards are fingerprinted alongside the train steps
    (docs/SERVING.md "Analyzer contract"): a world-divisible bucket
    compiles to exactly the two stats reductions (one [C] vector, one
    scalar; identical full-mesh groups, add) with nothing else, a
    sub-world bucket compiles to ZERO collectives, and the donated
    ServeStats leaves are proven aliased in both."""
    _, artifact = repo_hlo
    serve = {k: v for k, v in artifact["programs"].items()
             if k.startswith("serve_step")}
    assert set(serve) == {"serve_step@b16", "serve_step@b2"}
    big, small = serve["serve_step@b16"], serve["serve_step@b2"]
    # Fan-out bucket: batch sharded over data; only the stats reduce.
    assert big["counts"] == {"all-reduce": 2}, big["counts"]
    assert big["grad_reduce_ops"] == 1 and big["metric_allreduce_ops"] == 1
    groups = {op["replica_groups"] for op in big["collectives"]}
    reductions = {op["reduction"] for op in big["collectives"]}
    assert len(groups) == 1 and reductions == {"add"}, (groups, reductions)
    # Sub-world bucket: replicated compute, zero collectives.
    assert small["counts"] == {}, small["counts"]
    # Donated-buffer forward: the ServeStats pytree aliases in place.
    for name, rec in serve.items():
        assert rec["aliased_inputs"] == rec["donated_inputs"] == 2, (
            name, rec)
    assert big["digest"] != small["digest"]


def test_shipped_sharded_steps_have_scatter_update_gather_schedule(repo_hlo):
    """Sharded-mode train-step modules compile to the second legal
    schedule: one combinable reduce-scatter group + one all-gather group
    over the identical full-mesh replica groups, the two metric scalars,
    and NO non-scalar all-reduce (the gradient path really went through
    the scatter)."""
    _, artifact = repo_hlo
    sharded = {k: v for k, v in artifact["programs"].items()
               if v["update_sharding"] == "sharded"
               and v.get("wire", "f32") != "int8"}
    assert sharded, "no sharded programs in the shipped artifact"
    for name, rec in sharded.items():
        counts = rec["counts"]
        assert set(counts) == {"reduce-scatter", "all-gather", "all-reduce"}, (
            name, counts)
        by_kind = {}
        for op in rec["collectives"]:
            by_kind.setdefault(op["kind"], []).append(op)
        # One combinable group per collective kind, scatter == gather.
        scatter_groups = {op["replica_groups"]
                          for op in by_kind["reduce-scatter"]}
        gather_groups = {op["replica_groups"] for op in by_kind["all-gather"]}
        assert len(scatter_groups) == 1 and scatter_groups == gather_groups, (
            name, scatter_groups, gather_groups)
        assert all(op["reduction"] == "add"
                   for op in by_kind["reduce-scatter"]), name
        # Every all-reduce left is a declared metric scalar: 2 for the
        # plain programs, 3 with the sentinel (its cross-shard grad-norm
        # psum is the one collective guardrails add — see
        # `test_sentinel_programs_in_artifact`).
        declared = 3 if "sentinel" in name else 2
        assert len(by_kind["all-reduce"]) == rec["metric_allreduce_ops"] \
            == declared, (name, rec["metric_allreduce_ops"])
        assert rec["grad_reduce_ops"] == len(by_kind["reduce-scatter"]) >= 1
        # Donation survives the sharded layout: opt-state shards alias too.
        assert rec["aliased_inputs"] == rec["donated_inputs"] > 0, name


def test_shipped_int8_steps_have_quantized_schedule(repo_hlo):
    """The quantized-wire programs (`train.collective_dtype=int8`) compile
    to the THIRD legal schedule: int8 payload all-to-alls + f32 scale
    all-to-alls over the one full-mesh group for the quantizable leaves,
    plain reduce-scatters for the small-leaf fallback, the params
    all-gather, 4 declared metric scalars (loss, correct, overflow, clip;
    +1 for the sentinel's grad-norm psum) — and NO non-scalar all-reduce
    (every gradient leaf really went through a scatter path). Donation
    survives, residual buffers included."""
    _, artifact = repo_hlo
    int8 = {k: v for k, v in artifact["programs"].items()
            if v.get("wire") == "int8"}
    assert set(int8) == {
        "train_step[shard_map,sharded,int8]@accum1",
        "multi_step[sharded,int8]@w2",
        "train_step[shard_map,sharded,int8,sentinel]@accum1",
        "train_step[shard_map,sharded,int8,bucketed]@accum1",
    }
    for name, rec in int8.items():
        counts = rec["counts"]
        assert counts.get("all-to-all", 0) >= 2, (name, counts)
        by_kind = {}
        for op in rec["collectives"]:
            by_kind.setdefault(op["kind"], []).append(op)
        payload = [op for op in by_kind["all-to-all"] if "s8[" in op["shape"]]
        scales = [op for op in by_kind["all-to-all"] if "f32[" in op["shape"]]
        assert payload, (name, "no int8 payload exchange compiled")
        assert len(payload) + len(scales) == len(by_kind["all-to-all"])
        # One exchange group, matching the params gather's.
        groups = {op["replica_groups"] for op in by_kind["all-to-all"]}
        gather_groups = {op["replica_groups"] for op in by_kind["all-gather"]}
        assert len(groups) == 1 and groups == gather_groups, (
            name, groups, gather_groups)
        # Small-leaf fallback keeps the uncompressed scatter — except in
        # the bucketed schedule when every bucket clears the quantization
        # threshold (small leaves compress INSIDE their bucket, which is
        # the bucketed world's point; the recorded layout says which).
        buckets = rec.get("buckets")
        expect_rs = (any(b["wire"] != "int8" for b in buckets)
                     if buckets is not None else True)
        assert bool(by_kind.get("reduce-scatter")) == expect_rs, name
        non_scalar_ar = [op for op in by_kind.get("all-reduce", [])
                         if "[]" not in op["shape"]]
        assert non_scalar_ar == [], (name, non_scalar_ar)
        declared = 5 if "sentinel" in name else 4
        assert rec["metric_allreduce_ops"] == declared, (
            name, rec["metric_allreduce_ops"])
        # Donation survives the residual state: every donated leaf —
        # params, opt shards, AND the f32[world, qpad] residuals — aliases.
        assert rec["aliased_inputs"] == rec["donated_inputs"] > 0, name
    # The wire format is fingerprint-visible: an int8-configured rank
    # cannot impersonate an uncompressed one (DP304 catches the config
    # divergence before the first mismatched collective deadlocks).
    progs = artifact["programs"]
    assert (progs["train_step[shard_map,sharded,int8]@accum1"]["digest"]
            != progs["train_step[shard_map,sharded]@accum1"]["digest"])
    # ... and so is the bucket layout: a rank whose train.bucket_mb
    # diverged compiles a different ordered schedule.
    assert (progs["train_step[shard_map,sharded,int8,bucketed]@accum1"]
            ["digest"]
            != progs["train_step[shard_map,sharded,int8]@accum1"]["digest"])


def test_no_int8_wire_ops_outside_opted_in_programs(repo_hlo):
    """The blanket no-leak guarantee: across EVERY shipped program that did
    not opt into the quantized wire — GSPMD and shard_map train steps,
    sharded f32/bf16 steps, multi-step windows, eval, serve buckets,
    sentinel variants — the compiled module contains zero all-to-all ops
    and zero int8-typed collectives of any kind. Compression can never
    silently leak into a program that didn't ask for it."""
    _, artifact = repo_hlo
    checked = 0
    for name, rec in artifact["programs"].items():
        if rec.get("wire") == "int8":
            continue
        checked += 1
        assert "all-to-all" not in rec["counts"], (name, rec["counts"])
        int8_ops = [op for op in rec["collectives"]
                    if "s8[" in op["shape"] or "u8[" in op["shape"]]
        assert int8_ops == [], (name, int8_ops)
    assert checked >= 10  # the full non-quantized program matrix


def test_dp301_fires_on_int8_leak_and_missing_payload():
    """DP301's int8 rules both ways: an all-to-all in a NON-int8 program
    is flagged as a compression leak, and an int8-declared program with no
    s8 exchange is flagged as silently uncompressed."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel import dist
    from tpu_dp.train.step import _shard_map

    mesh = dist.data_mesh()

    def leak(g):
        q = jnp.clip(jnp.round(g), -127, 127).astype(jnp.int8)
        qx = jax.lax.all_to_all(q.reshape(8, -1), dist.DATA_AXIS,
                                split_axis=0, concat_axis=0, tiled=True)
        return jnp.sum(qx.astype(jnp.float32), axis=0)

    fn = jax.jit(_shard_map(leak, mesh, (P(dist.DATA_AXIS),),
                            P(dist.DATA_AXIS)))
    text, _, _ = hlo.lower_and_compile(
        fn, (jnp.zeros((8, 64), jnp.float32),))
    findings, _ = hlo.analyze_module(
        text, label="leak", where=("x.py", 1), world=8,
        update_sharding="sharded",
    )
    assert any("leaked" in f.message and f.rule == "DP301"
               for f in findings), findings

    # Same module declared int8 passes the leak rule...
    ok, rec = hlo.analyze_module(
        text, label="ok", where=("x.py", 1), world=8,
        update_sharding="sharded", wire="int8",
    )
    assert not any("leaked" in f.message for f in ok)
    assert rec["wire"] == "int8"

    # ...and an int8-declared program with NO s8 exchange fires.
    def plain(g):
        flat = jnp.pad(g.reshape(-1), (0, (-g.size) % 8))
        shard = jax.lax.psum_scatter(flat, dist.DATA_AXIS,
                                     scatter_dimension=0, tiled=True)
        return jax.lax.all_gather(shard, dist.DATA_AXIS, axis=0,
                                  tiled=True)[: g.size]

    fn2 = jax.jit(_shard_map(plain, mesh, (P(),), P()))
    text2, _, _ = hlo.lower_and_compile(fn2, (jnp.zeros((64,), jnp.float32),))
    findings2, _ = hlo.analyze_module(
        text2, label="uncompressed", where=("x.py", 1), world=8,
        update_sharding="sharded", wire="int8", expect_grad_reduce=True,
    )
    assert any("NO int8" in f.message for f in findings2), findings2


def test_sentinel_programs_in_artifact(repo_hlo):
    """The guardrail sentinel variants are fingerprinted alongside the
    plain steps (docs/RESILIENCE.md "Guardrails"): replicated/GSPMD
    sentinels add ZERO collectives (health computed from already-reduced
    gradients — same 2 metric scalars, all-reduce-only schedule), the
    sharded sentinel adds exactly ONE scalar psum (the cross-shard
    grad-norm sum), and donation survives the guarded select in every
    variant (the skip path's jnp.where must not cost double params
    memory)."""
    _, artifact = repo_hlo
    progs = artifact["programs"]
    sentinel = {k: v for k, v in progs.items() if "sentinel" in k}
    assert set(sentinel) == {
        "train_step[gspmd,sentinel]@accum1",
        "train_step[shard_map,sentinel]@accum1",
        "train_step[shard_map,sharded,sentinel]@accum1",
        "train_step[shard_map,sharded,int8,sentinel]@accum1",
        "multi_step[sentinel]@w2",
    }
    for name, rec in sentinel.items():
        assert rec["aliased_inputs"] == rec["donated_inputs"] > 0, name
        if rec.get("wire", "f32") == "int8":
            # Sharded sentinel's 3 plus the codec's overflow/clip psums.
            assert rec["metric_allreduce_ops"] == 5, name
        elif rec["update_sharding"] == "sharded":
            assert rec["metric_allreduce_ops"] == 3, name
        else:
            assert set(rec["counts"]) <= {"all-reduce"}, (name, rec["counts"])
            assert rec["metric_allreduce_ops"] == 2, name
    # The sharded sentinel's extra scalar is fingerprint-visible: a
    # guard-enabled rank cannot impersonate a guard-off one (DP304 would
    # catch the config divergence before the first deadlocked collective).
    assert (sentinel["train_step[shard_map,sharded,sentinel]@accum1"]["digest"]
            != progs["train_step[shard_map,sharded]@accum1"]["digest"])


def test_fingerprint_distinguishes_update_sharding_modes(repo_hlo):
    """The collective-schedule digest separates the two legal schedules:
    the sharded step cannot impersonate the replicated one (DP304's
    cross-rank check would catch a mode-diverged rank)."""
    _, artifact = repo_hlo
    progs = artifact["programs"]
    d_repl = progs["train_step[shard_map]@accum1"]["digest"]
    d_shard = progs["train_step[shard_map,sharded]@accum1"]["digest"]
    assert d_repl != d_shard
    assert progs["train_step[shard_map,sharded]@accum1"][
        "update_sharding"] == "sharded"


def test_dp301_fires_on_mismatched_scatter_gather_axes():
    """A sharded-update program whose reduce-scatter and all-gather run
    over different axes (the dp306 fixture's bug) — and one whose gradient
    bypassed the scatter into a plain all-reduce — both fail DP301's
    sharded-mode classification."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from tpu_dp.train.step import _shard_map

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh2d = Mesh(devices, ("data", "model"))

    def bad_axes(g):
        shard = jax.lax.psum_scatter(g, "model", scatter_dimension=0,
                                     tiled=True)
        return jax.lax.all_gather(shard - 0.1 * shard, "data", axis=0,
                                  tiled=True)[: g.size]

    fn = jax.jit(_shard_map(bad_axes, mesh2d, (P(),), P()))
    text, _, _ = hlo.lower_and_compile(fn, (jnp.zeros((32,), jnp.float32),))
    findings, _ = hlo.analyze_module(
        text, label="bad", where=("x.py", 1), world=8,
        update_sharding="sharded", expect_grad_reduce=True,
    )
    assert any("do not match all-gather replica groups" in f.message
               for f in findings), findings
    assert all(f.rule == "DP301" for f in findings)

    # Gradient bypassing the scatter: a non-scalar all-reduce in sharded
    # mode is its own DP301.
    from tpu_dp.parallel import collectives as coll
    from tpu_dp.parallel import dist

    mesh1d = dist.data_mesh()

    def bypass(g):
        return coll.pmean(g, dist.DATA_AXIS)

    fn2 = jax.jit(_shard_map(bypass, mesh1d, (P(dist.DATA_AXIS),), P()))
    text2, _, _ = hlo.lower_and_compile(fn2, (jnp.zeros((16, 4),
                                                        jnp.float32),))
    findings2, _ = hlo.analyze_module(
        text2, label="bypass", where=("x.py", 1), world=8,
        update_sharding="sharded",
    )
    assert any("bypassed the reduce-scatter" in f.message
               for f in findings2), findings2


def test_dp301_accepts_legal_sharded_schedule_unit():
    """The minimal legal sharded schedule (scatter → update → gather over
    one axis) passes sharded-mode DP301 — and fails replicated-mode DP301
    (the schedule split really keys off the declared mode)."""
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel import dist
    from tpu_dp.train.step import _shard_map

    mesh = dist.data_mesh()

    def good(g):
        flat = jnp.pad(g.reshape(-1), (0, (-g.size) % 8))
        shard = jax.lax.psum_scatter(flat, dist.DATA_AXIS,
                                     scatter_dimension=0, tiled=True) / 8.0
        new = shard - 0.1 * shard
        full = jax.lax.all_gather(new, dist.DATA_AXIS, axis=0, tiled=True)
        return full[: g.size].reshape(g.shape)

    fn = jax.jit(_shard_map(good, mesh, (P(),), P()))
    text, _, _ = hlo.lower_and_compile(fn, (jnp.zeros((30,), jnp.float32),))
    ok, _ = hlo.analyze_module(text, label="good", where=("x.py", 1),
                               world=8, update_sharding="sharded",
                               expect_grad_reduce=True)
    assert ok == []
    bad, _ = hlo.analyze_module(text, label="good-as-repl",
                                where=("x.py", 1), world=8,
                                update_sharding="replicated",
                                expect_grad_reduce=True)
    assert bad, "replicated-mode DP301 accepted a scatter/gather schedule"


def test_artifact_records_compile_stats(repo_hlo):
    _, artifact = repo_hlo
    for rec in artifact["programs"].values():
        assert rec["lowering_ms"] >= 0
        assert rec["compile_ms"] >= 0
    assert len(artifact["digest"]) == 64


# -- 2. fingerprints -----------------------------------------------------

def _compile_text(fn, *args):
    text, _, _ = hlo.lower_and_compile(jax.jit(fn), args)
    return text


def test_schedule_digest_is_deterministic():
    from tpu_dp.parallel import collectives, dist
    from tpu_dp.train.step import _shard_map

    mesh = dist.data_mesh()
    from jax.sharding import PartitionSpec as P

    def per_shard(x):
        return collectives.psum(x, dist.DATA_AXIS)

    def build():
        f = jax.jit(_shard_map(per_shard, mesh, (P(dist.DATA_AXIS),), P()))
        text, _, _ = hlo.lower_and_compile(
            f, (jnp.zeros((16, 4), jnp.float32),)
        )
        return hlo.schedule_digest(hlo.collect_ops(text))

    d1, d2 = build(), build()
    assert d1 == d2
    assert len(d1) == 64
    # A different program digests differently.
    d3 = hlo.schedule_digest(
        hlo.collect_ops(_compile_text(lambda x: x * 2, jnp.zeros((4,))))
    )
    assert d3 != d1


def test_count_collectives_sees_the_allreduce():
    from tpu_dp.parallel import collectives, dist
    from tpu_dp.train.step import _shard_map
    from jax.sharding import PartitionSpec as P

    mesh = dist.data_mesh()
    f = jax.jit(_shard_map(
        lambda x: collectives.psum(x, dist.DATA_AXIS),
        mesh, (P(dist.DATA_AXIS),), P(),
    ))
    text, stats, _ = hlo.lower_and_compile(f, (jnp.zeros((16,), jnp.float32),))
    assert hlo.count_collectives(text).get("all-reduce", 0) >= 1
    assert stats["compile_ms"] >= 0


def test_verify_collective_fingerprint_single_process():
    from tpu_dp.parallel import dist

    digest = "ab" * 32
    assert dist.verify_collective_fingerprint(digest) == digest
    with pytest.raises(ValueError):
        dist.verify_collective_fingerprint("not-a-digest")


def test_verify_collective_fingerprint_every_rank_sees_mismatch(monkeypatch):
    """The matching rank (rank 0) must raise too — otherwise it sails past
    the check and hangs at its first collective waiting for the dead peer,
    the exact deadlock the hook exists to prevent."""
    import numpy as np
    from jax.experimental import multihost_utils

    from tpu_dp.parallel import dist

    digest = "ab" * 32
    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)
    monkeypatch.setattr(dist.jax, "process_index", lambda: 0)
    gathered = np.stack([
        np.frombuffer(bytes.fromhex(digest), np.uint8),  # this rank (0)
        np.zeros(32, np.uint8),                          # divergent rank 1
    ])
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: gathered)
    with pytest.raises(RuntimeError, match="divergent ranks: \\[1\\]"):
        dist.verify_collective_fingerprint(digest)


def test_program_fingerprint_accepts_shape_structs():
    """The trainer's startup hook lowers from ShapeDtypeStructs — no real
    buffers needed to fingerprint the program about to run."""
    fp = hlo.program_fingerprint(
        jax.jit(lambda x: x + 1),
        (jax.ShapeDtypeStruct((8,), jnp.float32),),
    )
    assert len(fp) == 64


# -- 3. DP303 catches dropped donation -----------------------------------

def test_dp303_fires_on_dropped_donation():
    jitted = jax.jit(lambda x: (x.astype(jnp.bfloat16),),
                     donate_argnums=(0,))
    text, _, warns = hlo.lower_and_compile(
        jitted, (jnp.zeros((32, 32), jnp.float32),)
    )
    findings, record = hlo.analyze_module(
        text, label="drop", where=("x.py", 1), world=8,
        donated_leaves=1, donation_warnings=warns,
    )
    assert [f.rule for f in findings] == ["DP303"]
    assert record["aliased_inputs"] == 0
    # The XLA lowering warning is surfaced in the finding, not swallowed.
    assert "donated buffers were not usable" in findings[0].message


def test_dp303_clean_on_real_donation():
    jitted = jax.jit(lambda x: (x * 2,), donate_argnums=(0,))
    text, _, warns = hlo.lower_and_compile(
        jitted, (jnp.zeros((32, 32), jnp.float32),)
    )
    findings, record = hlo.analyze_module(
        text, label="ok", where=("x.py", 1), world=8,
        donated_leaves=1, donation_warnings=warns,
    )
    assert findings == []
    assert record["aliased_inputs"] == 1


# -- 4. RecompileGuard ---------------------------------------------------

def test_recompile_guard_counts_only_post_warmup_retraces():
    logged: list[str] = []
    guard = RecompileGuard(jax.jit(lambda x: x * 2), name="g",
                           warmup_calls=1, logger=logged.append)
    x4, x8 = jnp.zeros((4,)), jnp.zeros((8,))
    guard(x4)
    guard(x4)
    assert guard.retraces == 0 and logged == []
    guard(x8)  # new shape -> real retrace
    assert guard.retraces == 1
    assert len(logged) == 1 and "retrace" in logged[0]
    guard(x8)  # cached now
    assert guard.retraces == 1
    stats = guard.stats()
    assert stats["calls"] == 4 and stats["retraces"] == 1


def test_recompile_guard_raise_mode():
    guard = RecompileGuard(jax.jit(lambda x: x + 1), on_retrace="raise")
    guard(jnp.zeros((4,)))
    with pytest.raises(RecompileError):
        guard(jnp.zeros((16,)))


def test_recompile_guard_proxies_jit_introspection():
    jitted = jax.jit(lambda x: x + 1)
    guard = RecompileGuard(jitted)
    # AOT lowering still reachable through the guard (trainer fingerprint).
    assert guard.lower(jnp.zeros((4,))).compile() is not None
    with pytest.raises(ValueError):
        RecompileGuard(jitted, on_retrace="explode")


def test_trainer_wraps_train_step_in_guard(tmp_path):
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 64
    c.data.synthetic_test_size = 32
    c.data.batch_size = 16
    c.train.epochs = 1
    c.train.ckpt_dir = str(tmp_path / "ck")
    c.train.verify_fingerprint = True  # single-process: digest + log only
    trainer = Trainer(c)
    assert isinstance(trainer.train_step, RecompileGuard)
    assert trainer.train_step.retraces == 0

    c2 = Config()
    c2.data.dataset = "synthetic"
    c2.data.synthetic_train_size = 64
    c2.data.synthetic_test_size = 32
    c2.data.batch_size = 16
    c2.train.ckpt_dir = str(tmp_path / "ck2")
    c2.train.recompile_guard = "off"
    assert not isinstance(Trainer(c2).train_step, RecompileGuard)

    # Without drop_remainder the final partial batch (padded, weight leaf)
    # legitimately compiles a second variant every epoch: unguarded, so
    # 'raise' mode cannot kill a correct run at the end of epoch 1.
    c3 = Config()
    c3.data.dataset = "synthetic"
    c3.data.synthetic_train_size = 64
    c3.data.synthetic_test_size = 32
    c3.data.batch_size = 16
    c3.data.drop_remainder = False
    c3.train.ckpt_dir = str(tmp_path / "ck3")
    c3.train.recompile_guard = "raise"
    assert not isinstance(Trainer(c3).train_step, RecompileGuard)


# -- 5. DP305 static lint ------------------------------------------------

def test_dp305_flags_jit_in_loop_and_fresh_lambda():
    src = (
        "import jax\n"
        "def f(xs):\n"
        "    out = []\n"
        "    for x in xs:\n"
        "        out.append(jax.jit(step)(x))\n"
        "    return out\n"
        "def g(x):\n"
        "    return jax.jit(lambda v: v * v)(x)\n"
    )
    findings = recompile.lint_source("x.py", src)
    assert [(f.rule, f.line) for f in findings] == [("DP305", 5),
                                                    ("DP305", 8)]
    assert findings[0].symbol == "f" and findings[1].symbol == "g"


def test_dp305_does_not_flag_factory_idiom():
    """`make_train_step` returning jax.jit(named_fn) once is the shipped
    pattern — a named nested function jitted outside a loop is fine, and so
    is a module-scope jit(lambda) (one-time cost)."""
    src = (
        "import jax\n"
        "def make_step(model):\n"
        "    def step(state, batch):\n"
        "        return state\n"
        "    return jax.jit(step, donate_argnums=(0,))\n"
        "_barrier = jax.jit(lambda x: x.sum())\n"
    )
    assert recompile.lint_source("x.py", src) == []


def test_dp305_pragma_suppresses():
    src = (
        "import jax\n"
        "def f(xs):\n"
        "    for x in xs:\n"
        "        jax.jit(g)(x)  # dplint: allow(DP305)\n"
    )
    assert recompile.lint_source("x.py", src) == []


# -- 6. bench compile stats ----------------------------------------------

def test_bench_compile_with_flops_reports_stats():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    exe, _, stats = bench.compile_with_flops(
        jax.jit(lambda x: x @ x), jnp.zeros((16, 16), jnp.float32)
    )
    assert exe is not None
    assert stats["lowering_ms"] >= 0 and stats["compile_ms"] >= 0
    assert isinstance(stats["hlo_collectives"], dict)


# -- 7. the CI lane's artifact emission ----------------------------------

@pytest.mark.slow
def test_cli_writes_fingerprint_artifact(tmp_path):
    out = tmp_path / "fp.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_dp.analysis",
         os.path.join(REPO, "tpu_dp"), "--json", "--accum-steps", "1",
         "--fingerprint-out", str(out)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    artifact = json.loads(out.read_text())
    assert set(artifact["programs"]) >= {"train_step[gspmd]@accum1",
                                         "eval_step"}
