"""Utility-layer tests: throughput meter, profiler hook, rank-0 logging.

These subsystems exist because SURVEY.md §5 marks tracing/profiling ABSENT
in the reference while BASELINE.json's north-star metric is images/sec/chip
— the meter's honesty (dispatch vs completion fencing) is load-bearing for
every reported number.
"""

import time

import jax
import numpy as np
import pytest

from tpu_dp.utils import ThroughputMeter, log0, print0, profile_trace


def test_meter_excludes_warmup_and_counts_images():
    m = ThroughputMeter(warmup_steps=2)
    for _ in range(2):  # warmup (compile) steps: excluded
        m.step(100)
    assert m.measured_steps == 0 and m.images_per_sec == 0.0
    for _ in range(5):
        m.step(100)
        time.sleep(0.002)
    m.mark()
    assert m.measured_steps == 5
    assert m.elapsed > 0
    # 500 images over the measured window; rate is finite and positive.
    assert m.images_per_sec == pytest.approx(500 / m.elapsed)
    assert m.step_time_ms == pytest.approx(m.elapsed / 5 * 1e3)


def test_meter_mark_extends_to_fence_time():
    """mark() after a device fence must extend the window past the last
    dispatch timestamp — the difference between dispatch rate and
    throughput on async transports."""
    m = ThroughputMeter(warmup_steps=0)  # clamped to 1: a rate needs a start
    assert m.warmup_steps == 1
    m.step(10)
    m.step(10)
    dispatch_elapsed = m.elapsed
    time.sleep(0.01)  # "device still executing"
    m.mark()
    assert m.elapsed > dispatch_elapsed
    m.reset()
    assert m.measured_steps == 0 and m.elapsed == 0.0


def test_profile_trace_writes_xla_trace(tmp_path):
    with profile_trace(str(tmp_path / "trace")):
        jax.block_until_ready(jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8)))
    produced = list((tmp_path / "trace").rglob("*"))
    assert produced, "profiler trace directory is empty"


def test_profile_trace_noop_without_dir():
    with profile_trace(None):
        pass  # must not require a profiler session


def test_rank0_print_and_log(capsys):
    print0("hello", "world")
    log0("logged %d", 7)
    out = capsys.readouterr().out
    assert "hello world" in out
