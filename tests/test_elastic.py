"""Elastic world size: in-process protocol/resplit/hardening tests.

The subprocess end-to-end (3 real processes, rank 2 preempted, survivors
finish on world 2 — `tests/test_multiprocess.py`) proves the whole loop;
these tests pin the pieces it is built from, each runnable in-process:

- `elastic_resplit` — the mid-epoch sampler re-split: exact coverage (no
  drops, no duplicates) across one and two world changes, lockstep step
  counts, fidelity to what `DataPipeline` actually consumed;
- `MembershipLedger` — the shared-filesystem protocol, driven by plain
  threads against one tmp dir: convergence, single-writer plans, timeout
  departure, exclusive-create races;
- the `leave:`/`preempt:` fault specs that make regroup testable without
  external signals;
- `find_latest`/`resume_latest` hardening against the torn step dirs a
  crash-mid-snapshot leaves behind;
- a full single-process `Trainer` departure: `leave:` fault → quiesce →
  final snapshot with membership lineage → `PreemptedError` (exit-143
  path), then `--resume=auto` completing bitwise-identically to an
  uninterrupted run.
"""

import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from tpu_dp.data.sampler import (
    ElasticTailSampler,
    ShardedSampler,
    elastic_resplit,
)
from tpu_dp.resilience.elastic import (
    ElasticError,
    MembershipLedger,
    MembershipRecord,
    QuiescePlan,
)

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------------------
# elastic_resplit: the re-split contract
# ---------------------------------------------------------------------------


def _consumed(E, world, steps, per_step, seed=7, epoch=0):
    """What the pipeline's shards actually consumed: first steps*per_step
    of every `ShardedSampler` shard stream."""
    out = []
    for r in range(world):
        s = ShardedSampler(E, world, r, shuffle=True, seed=seed)
        s.set_epoch(epoch)
        out.append(s.shard_indices()[: steps * per_step])
    return np.concatenate(out)


def test_resplit_exact_coverage_one_hop():
    E, B = 48, 4
    consumed = _consumed(E, 3, 2, B)
    tails = [elastic_resplit(E, True, 7, 0, B, [(3, 2)], 2, m)
             for m in range(2)]
    everything = np.concatenate([consumed, *tails])
    # Every sample of the epoch visited exactly once across the regroup.
    assert sorted(everything.tolist()) == list(range(E))
    # Lockstep: every survivor gets the identical step count.
    assert len(tails[0]) == len(tails[1]) == 12


def test_resplit_exact_coverage_two_hops():
    # 3 ranks for 2 steps, then 2 ranks for 1 step, then world 1.
    E, B = 48, 4
    consumed = _consumed(E, 3, 2, B)
    seg2 = [elastic_resplit(E, True, 7, 0, B, [(3, 2)], 2, m)[:B]
            for m in range(2)]
    tail = elastic_resplit(E, True, 7, 0, B, [(3, 2), (2, 1)], 1, 0)
    everything = np.concatenate([consumed, *seg2, tail])
    assert sorted(everything.tolist()) == list(range(E))


def test_resplit_lockstep_on_awkward_remainders():
    # Non-divisible everywhere: the split must still hand every survivor
    # the same whole-step count (unequal counts deadlock the mesh).
    for E, w0, s0, w1, B in [(50, 3, 1, 2, 4), (47, 3, 2, 2, 4),
                             (49, 4, 1, 3, 2), (31, 2, 3, 1, 4)]:
        tails = [elastic_resplit(E, True, 1, 5, B, [(w0, s0)], w1, m)
                 for m in range(w1)]
        assert len({len(t) for t in tails}) == 1, (E, w0, s0, w1)
        assert len(tails[0]) % B == 0
        # No duplicates within the re-split remainder itself, and nothing
        # that was already consumed reappears (E divisible: strict).
        consumed = set(_consumed(E, w0, s0, B, seed=1, epoch=5).tolist())
        if E % w0 == 0:
            joined = np.concatenate(tails).tolist()
            assert len(joined) == len(set(joined))
            assert not (set(joined) & consumed)


def test_resplit_matches_pipeline_consumption(cpu_mesh_1):
    """The re-split's model of "what was consumed" is bit-for-bit what
    `DataPipeline` feeds: resume a pipeline mid-epoch via an injected
    tail sampler and the union equals the uninterrupted epoch."""
    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.data.pipeline import DataPipeline

    ds = make_synthetic(48, 10, seed=0, name="resplit")
    pipe = DataPipeline(ds, batch_size=4, mesh=cpu_mesh_1, shuffle=True,
                        seed=7, prefetch=0)
    pipe.set_epoch(0)
    full = [np.asarray(b["label"]) for b in pipe]
    # Re-split after 2 of the 12 steps onto "world 1" (same process).
    idx = elastic_resplit(48, True, 7, 0, 4, [(1, 2)], 1, 0)
    tail_pipe = DataPipeline(ds, batch_size=4, mesh=cpu_mesh_1, shuffle=True,
                             seed=7, prefetch=0,
                             sampler=ElasticTailSampler(idx, 0))
    tail_pipe.set_epoch(0)
    tail = [np.asarray(b["label"]) for b in tail_pipe]
    np.testing.assert_array_equal(
        np.concatenate(full[:2] + tail), np.concatenate(full)
    )


def test_resplit_non_divisible_matches_uninterrupted_plan():
    """Fidelity on non-divisible sizes: the live sampler pads by
    wraparound (torch `DistributedSampler` parity — `DataPipeline` builds
    it with sampler-level drop_remainder=False regardless of its own step
    truncation), and the re-split reproduces that pad bit-for-bit. The
    interrupted epoch consumes the same NUMBER of samples as the
    uninterrupted plan with no sample exceeding its padded-stream count
    (nothing replayed, nothing invented); at the step-truncation seam the
    identity of the shed leftovers may swap — the same drop_remainder
    freedom every epoch end already has — bounded by one global batch."""
    from collections import Counter

    E, B, world = 51, 4, 2  # 51 % 2 != 0: one wraparound-pad duplicate
    plan = []  # the uninterrupted epoch's consumption, per live sampler
    padded = []
    for r in range(world):
        s = ShardedSampler(E, world, r, shuffle=True, seed=3)
        s.set_epoch(1)
        stream = s.shard_indices()
        padded.append(stream)
        plan.append(stream[: (len(stream) // B) * B])  # 6 whole steps
    consumed = _consumed(E, world, 3, B, seed=3, epoch=1)  # 3 steps ran
    tails = [elastic_resplit(E, True, 3, 1, B, [(world, 3)], world, m)
             for m in range(world)]
    got = Counter(np.concatenate([consumed, *tails]).tolist())
    want = Counter(np.concatenate(plan).tolist())
    assert sum(got.values()) == sum(want.values())  # same consumption count
    stream_counts = Counter(np.concatenate(padded).tolist())
    for sample, n in got.items():
        assert n <= stream_counts[sample], f"sample {sample} over-consumed"
    # Seam freedom: the swapped leftovers stay under one global batch.
    swapped = sum(((want - got) + (got - want)).values())
    assert swapped < 2 * world * B, swapped


def test_tail_sampler_refuses_reseed():
    s = ElasticTailSampler(np.arange(8), epoch=3)
    s.set_epoch(3)  # idempotent
    with pytest.raises(ValueError, match="pinned to epoch 3"):
        s.set_epoch(4)


def test_resplit_rejects_bad_lineage():
    with pytest.raises(ValueError, match="consumes"):
        elastic_resplit(16, True, 0, 0, 4, [(2, 99)], 1, 0)
    with pytest.raises(ValueError, match="out of range"):
        elastic_resplit(16, True, 0, 0, 4, [], 2, 5)


def test_resplit_grow_exact_coverage_shrink_then_grow():
    """The grow half of the re-split contract (ISSUE 12): 3 ranks run 2
    steps, the mesh shrinks to 2 for 1 step, then GROWS back to 3 — the
    union of everything consumed plus the grown tails is exactly the
    epoch, and every member of the grown world gets the identical step
    count."""
    E, B = 96, 4
    consumed3 = _consumed(E, 3, 2, B)                      # world 3, 2 steps
    seg2 = [elastic_resplit(E, True, 7, 0, B, [(3, 2)], 2, m)[:B]
            for m in range(2)]                             # world 2, 1 step
    tails = [elastic_resplit(E, True, 7, 0, B, [(3, 2), (2, 1)], 3, m)
             for m in range(3)]                            # grown back to 3
    everything = np.concatenate([consumed3, *seg2, *tails])
    # E = 96 is divisible by every world in the lineage: exactness is
    # total up to the min-shard truncation seam.
    joined = sorted(everything.tolist())
    assert len(joined) == len(set(joined)), "a sample consumed twice"
    shed = E - len(joined)
    assert shed < 3 * B, f"{shed} samples shed beyond one global batch"
    # Lockstep on the grown world: identical whole-step counts.
    assert len({len(t) for t in tails}) == 1
    assert len(tails[0]) % B == 0 and len(tails[0]) > 0


def test_resplit_grow_lockstep_on_awkward_remainders():
    """Grow hops with non-divisible sizes, including grow→grow and
    shrink→grow lineages: the re-split must still hand every member of
    the larger world the same whole-step count, consume nothing twice,
    and invent nothing (satellite: grow-segment unit oracle)."""
    from collections import Counter

    cases = [
        # (E, lineage, new_world, B)
        (50, [(2, 2)], 3, 4),             # plain grow 2→3
        (47, [(3, 1), (2, 2)], 3, 4),     # shrink 3→2 then grow 2→3
        (49, [(1, 3)], 4, 2),             # world 1 grows to 4
        (53, [(2, 1), (3, 2)], 5, 2),     # grow→grow
    ]
    for E, lineage, new_world, B in cases:
        tails = [elastic_resplit(E, True, 11, 2, B, lineage, new_world, m)
                 for m in range(new_world)]
        assert len({len(t) for t in tails}) == 1, (E, lineage, new_world)
        assert len(tails[0]) % B == 0
        # Nothing is invented: per-sample consumption (replayed lineage +
        # grown tails) never exceeds the padded stream's plan.
        base = ShardedSampler(E, 1, 0, shuffle=True, seed=11)
        base.set_epoch(2)
        stream_counts: Counter = Counter()
        remaining = base.shard_indices()
        consumed_all: list[np.ndarray] = []
        from tpu_dp.data.sampler import _pad_to_multiple

        for world, steps in lineage:
            stream = _pad_to_multiple(remaining, world)
            stream_counts.update(stream.tolist())
            shards = [stream[r::world] for r in range(world)]
            consumed_all += [s[: steps * B] for s in shards]
            remaining = np.concatenate([s[steps * B:] for s in shards])
        stream_counts.update(
            _pad_to_multiple(remaining, new_world).tolist()
        )
        got = Counter(np.concatenate(consumed_all + tails).tolist())
        # (the padded-stream multiset only ever grows, so this bounds
        # every hop's wraparound duplicates)
        for sample, n in got.items():
            assert n <= stream_counts[sample], (E, lineage, sample)


# ---------------------------------------------------------------------------
# MembershipLedger: the file protocol, exercised by real threads
# ---------------------------------------------------------------------------


def _converge(ledger: MembershipLedger, members, step0: int,
              leaving: bool, deadline_s: float = 20.0) -> QuiescePlan:
    """Drive one member's quiesce loop the way the trainer does: refresh
    the check-in (advancing its step, as a live rank would), try to
    publish, poll for the plan."""
    start = time.monotonic()
    step = step0
    while time.monotonic() - start < deadline_s:
        ledger.check_in(1, step, leaving, "graceful", window=1)
        plan = ledger.try_plan(1)
        if plan is None:
            ledger.maybe_publish_plan(
                1, members, train_epoch=0,
                timed_out=time.monotonic() - start > 2.0,
            )
            plan = ledger.try_plan(1)
        if plan is not None:
            return plan
        step += 1
        time.sleep(0.01)
    raise AssertionError("no plan within deadline")


def test_ledger_graceful_convergence_threads(tmp_path):
    members = [0, 1, 2]
    plans = {}

    def member(sid):
        led = MembershipLedger(tmp_path, sid)
        plans[sid] = _converge(led, members, step0=4 + sid, leaving=sid == 2)

    threads = [threading.Thread(target=member, args=(s,)) for s in members]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert set(plans) == {0, 1, 2}
    # Everyone adopted the ONE canonical plan.
    assert len({json.dumps(p.to_json(), sort_keys=True)
                for p in plans.values()}) == 1
    plan = plans[0]
    assert plan.flavor == "graceful"
    assert plan.leavers == (2,)
    assert plan.survivors == (0, 1)
    assert plan.departed == ()
    # The stop threshold clears every member's published position.
    assert plan.stop_step > max(4 + s for s in members)


def test_ledger_timeout_declares_departed(tmp_path):
    # Member 2 never checks in (hard death): the collection times out and
    # the plan demotes it to departed with a rollback flavor.
    members = [0, 1, 2]
    plans = {}

    def member(sid):
        led = MembershipLedger(tmp_path, sid)
        plans[sid] = _converge(led, members, step0=3, leaving=False)

    threads = [threading.Thread(target=member, args=(s,)) for s in (0, 1)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    plan = plans[0]
    assert plans[1].to_json() == plan.to_json()
    assert plan.flavor == "rollback"
    assert [d["sid"] for d in plan.departed] == [2]
    assert "no quiesce check-in" in plan.departed[0]["reason"]
    assert plan.survivors == (0, 1)


def test_ledger_suspect_reason_lands_in_plan(tmp_path):
    led0 = MembershipLedger(tmp_path, 0)
    led0.mark_suspect(1, 1, "heartbeat stale 42s")
    led0.check_in(1, 7, leaving=False, flavor="rollback")
    led0.maybe_publish_plan(1, [0, 1], train_epoch=0, timed_out=True)
    plan = led0.try_plan(1)
    assert plan.flavor == "rollback"
    assert plan.departed == ({"sid": 1, "reason": "heartbeat stale 42s"},)
    # Epoch-scoped accusation: the same file is inert for the NEXT
    # transition (a surviving false-positive must not re-trigger regroups
    # of every later epoch).
    assert led0.suspects(2) == {}
    assert led0.suspects(1) == {1: "heartbeat stale 42s"}


def test_ledger_plan_publish_is_exclusive(tmp_path):
    # Two racing publishers: exactly one plan file wins; the loser adopts.
    from tpu_dp.resilience.elastic import _exclusive_write_json

    path = tmp_path / "plan_e0001.json"
    a = _exclusive_write_json(path, {"who": "a"})
    b = _exclusive_write_json(path, {"who": "b"})
    assert a and not b
    assert json.loads(path.read_text()) == {"who": "a"}


def test_membership_record_roundtrip_and_epoch_await(tmp_path):
    led = MembershipLedger(tmp_path, 0)
    rec = led.write_initial([0, 1, 2], "127.0.0.1:9999")
    assert rec.epoch == 0 and rec.world == 3
    assert rec.rank_of(1) == 1
    nxt = MembershipRecord(
        epoch=1, members=(0, 2), coordinator="127.0.0.1:10000",
        departed=({"sid": 1, "reason": "preempted"},),
        resume={"epoch": 0, "steps_done": 4, "lineage": [[3, 4]],
                "global_step": 4, "snapshot_dir": "snap"},
        reason="graceful", ts=123.0,
    )
    led.publish_epoch(nxt)
    got = led.await_epoch(1, timeout_s=2)
    assert got.members == (0, 2)
    assert got.rank_of(2) == 1  # dense ranks reassigned, sids stable
    with pytest.raises(ElasticError, match="not a member"):
        got.rank_of(1)
    assert led.current().epoch == 1
    with pytest.raises(ElasticError, match="did not appear"):
        led.await_epoch(5, timeout_s=0.2)


def test_quiesce_ack_barrier(tmp_path):
    led0, led1 = MembershipLedger(tmp_path, 0), MembershipLedger(tmp_path, 1)
    led0.ack_quiesced(1)
    assert led0.await_quiesced(1, [0, 1], timeout_s=0.3) == [1]  # 1 missing
    led1.ack_quiesced(1)
    assert led0.await_quiesced(1, [0, 1], timeout_s=2) == []


# ---------------------------------------------------------------------------
# grow: join requests, fencing, grow plans (ISSUE 12)
# ---------------------------------------------------------------------------


def test_join_claim_is_exclusive_per_transition(tmp_path):
    led = MembershipLedger(tmp_path, 2)
    assert led.publish_join(1, 2, token="aaa", generation=tmp_path.name)
    # A second incarnation racing for the same seat loses the claim and
    # can read whose token holds it.
    assert not led.publish_join(1, 2, token="bbb", generation=tmp_path.name)
    assert led.join_request(1, 2)["token"] == "aaa"


def test_zombie_from_retired_generation_is_refused(tmp_path):
    """The fencing acceptance (ISSUE 12): a zombie whose worldview is a
    RETIRED generation — its join request names the old generation dir —
    must be refused admission with a typed verdict, never admitted."""
    led = MembershipLedger(tmp_path / "gen_live", 0)
    led.write_initial([0, 1], None)
    # The zombie constructed its request from the stale incarnation's
    # view: it names gen_retired while publishing into the live dir.
    zled = MembershipLedger(tmp_path / "gen_live", 7)
    zled.publish_join(1, 7, token="zzz", generation="gen_retired")
    accepted = led.validate_joins(1, [0, 1])
    assert accepted == {}
    refusal = led.join_refusal(1, 7)
    assert refusal is not None
    assert "stale generation" in refusal["reason"]
    # The verdict is final for the transition: even if the zombie's view
    # somehow became right, this epoch never admits it.
    assert led.validate_joins(1, [0, 1]) == {}
    # ... and a refused request never triggers a grow plan.
    led.check_in(1, 5, leaving=False, flavor="graceful")
    led.maybe_publish_plan(1, [0, 1], train_epoch=0, timed_out=True)
    plan = led.try_plan(1)
    assert plan.flavor == "rollback"  # member 1 timed out, not a grow
    assert plan.joiners == ()


def test_zombie_targeting_retired_epoch_is_refused(tmp_path):
    """The fencing a REAL zombie trips: it built its request from a
    retired record, so it targets a transition the live run is past —
    refused with a typed verdict by the members' hygiene sweep. A claim
    at exactly the current epoch (the shrink-deferred case, whose owner
    is re-targeting) is deliberately spared."""
    led = MembershipLedger(tmp_path, 0)
    led.write_initial([0, 1], None)
    # Epoch 2 ADMITTED sid 9 — its (consumed) join file must never be
    # retro-refused, or every successful grow would leave a phantom
    # "zombie" verdict in the forensic record.
    led.publish_epoch(MembershipRecord(
        epoch=2, members=(0, 1, 9), coordinator=None,
        joined=({"sid": 9, "token": "ok"},), ts=time.time()))
    MembershipLedger(tmp_path, 9).publish_join(
        2, 9, token="ok", generation=tmp_path.name)
    led.publish_epoch(MembershipRecord(
        epoch=3, members=(0, 1, 9), coordinator=None, ts=time.time()))
    zombie = MembershipLedger(tmp_path, 7)
    zombie.publish_join(1, 7, token="old", generation=tmp_path.name)
    deferred = MembershipLedger(tmp_path, 8)
    deferred.publish_join(3, 8, token="cur", generation=tmp_path.name)
    # sid 5's e1 request was deferred (shrink won) and it was admitted
    # only at a LATER epoch: its stale first file must be spared because
    # it is a current member now.
    MembershipLedger(tmp_path, 5).publish_join(
        1, 5, token="def", generation=tmp_path.name)
    led.refuse_stale_joins(current_epoch=3, members=[0, 1, 5, 9])
    refusal = led.join_refusal(1, 7)
    assert refusal is not None and "stale epoch" in refusal["reason"]
    assert led.join_refusal(3, 8) is None  # current-epoch claim spared
    assert led.join_refusal(2, 9) is None  # admitted claim spared
    assert led.join_refusal(1, 5) is None  # deferred-then-admitted spared


def test_join_refused_when_sid_is_live_member(tmp_path):
    led = MembershipLedger(tmp_path, 0)
    led.write_initial([0, 1], None)
    led.publish_join(1, 1, token="ttt", generation=tmp_path.name)
    assert led.validate_joins(1, [0, 1]) == {}
    assert "live member" in led.join_refusal(1, 1)["reason"]


def test_join_refused_beyond_max_world(tmp_path):
    led = MembershipLedger(tmp_path, 0)
    led.write_initial([0, 1], None)
    led.publish_join(1, 2, token="t2", generation=tmp_path.name)
    led.publish_join(1, 3, token="t3", generation=tmp_path.name)
    accepted = led.validate_joins(1, [0, 1], max_world=3)
    # Deterministic lowest-sid-first admission under the bound.
    assert sorted(accepted) == [2]
    assert "elastic_max_world" in led.join_refusal(1, 3)["reason"]


def test_grow_plan_from_valid_join(tmp_path):
    led = MembershipLedger(tmp_path, 0)
    led.write_initial([0, 1], None)
    joiner = MembershipLedger(tmp_path, 2)
    joiner.publish_join(1, 2, token="tok", generation=tmp_path.name)
    for sid in (0, 1):
        MembershipLedger(tmp_path, sid).check_in(
            1, 6 + sid, leaving=False, flavor="graceful")
    led.maybe_publish_plan(1, [0, 1], train_epoch=0, timed_out=False)
    plan = led.try_plan(1)
    assert plan is not None and plan.flavor == "grow"
    assert plan.joiners == (2,)
    assert plan.survivors == (0, 1, 2)
    assert plan.incumbents == (0, 1)
    assert plan.leavers == () and plan.departed == ()
    # Stop threshold clears every *member's* published position (the
    # joiner is not stepping and publishes none).
    assert plan.stop_step > 7


def test_shrink_wins_over_concurrent_join(tmp_path):
    """The join-during-shrink race has an explicit answer: a transition
    with a leaver resolves the shrink alone; the pending join is deferred
    (the joiner re-targets the next epoch)."""
    led = MembershipLedger(tmp_path, 0)
    led.write_initial([0, 1, 2], None)
    joiner = MembershipLedger(tmp_path, 5)
    joiner.publish_join(1, 5, token="tok", generation=tmp_path.name)
    for sid, leaving in ((0, False), (1, False), (2, True)):
        MembershipLedger(tmp_path, sid).check_in(
            1, 4, leaving=leaving, flavor="graceful")
    led.maybe_publish_plan(1, [0, 1, 2], train_epoch=0, timed_out=False)
    plan = led.try_plan(1)
    assert plan.flavor == "graceful"
    assert plan.leavers == (2,)
    assert plan.joiners == () and 5 not in plan.survivors
    # No refusal either: the claim simply rides to the next transition.
    assert led.join_refusal(1, 5) is None


def test_request_join_admission_handshake_threads(tmp_path):
    """The joiner's client half against a live member thread: request →
    grow plan → epoch record echoing the token → admitted."""
    from tpu_dp.resilience.elastic import request_join

    gen = tmp_path / "gen_x"
    led = MembershipLedger(gen, 0)
    led.write_initial([0], None)

    def member():
        # A world-1 member converging a grow transition the way the
        # trainer does: poll, check in, publish, establish.
        deadline = time.monotonic() + 20
        step = 3
        while time.monotonic() < deadline:
            joins = led.validate_joins(1, [0])
            if joins:
                break
            time.sleep(0.01)
        while time.monotonic() < deadline:
            led.check_in(1, step, leaving=False, flavor="graceful")
            led.maybe_publish_plan(1, [0], train_epoch=0, timed_out=False)
            plan = led.try_plan(1)
            if plan is not None:
                break
            step += 1
            time.sleep(0.01)
        req = led.join_request(1, 2)
        rec = MembershipRecord(
            epoch=1, members=tuple(sorted(plan.survivors)),
            coordinator="127.0.0.1:1",
            joined=({"sid": 2, "token": req["token"]},),
            service_sid=0, resume={"epoch": 0, "steps_done": plan.stop_step,
                                   "lineage": [], "global_step":
                                   plan.stop_step, "snapshot_dir": None},
            reason="grow", ts=time.time(),
        )
        led.publish_epoch(rec)

    t = threading.Thread(target=member)
    t.start()
    record, token = request_join(gen, 2, timeout_s=15)
    t.join(timeout=20)
    assert record.epoch == 1 and record.members == (0, 2)
    assert record.joined == ({"sid": 2, "token": token},)
    assert record.service_sid == 0
    assert record.rank_of(2) == 1


def test_join_ready_gate(tmp_path):
    """The incumbents' commit gate: a grown bootstrap starts only once
    every admitted joiner signalled ready (a coordination connect with an
    absent party is a LOG(FATAL), not a catchable error)."""
    led = MembershipLedger(tmp_path, 0)
    assert led.await_join_ready(2, [5], timeout_s=0.2) == [5]  # ghost
    MembershipLedger(tmp_path, 5).confirm_join_ready(2, 5)
    assert led.await_join_ready(2, [5], timeout_s=2) == []


def test_request_join_refusal_is_typed(tmp_path):
    from tpu_dp.resilience.elastic import request_join

    gen = tmp_path / "gen_y"
    led = MembershipLedger(gen, 0)
    led.write_initial([0], None)

    def refuser():
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if led.join_request(1, 3) is not None:
                led.refuse_join(1, 3, "world at resilience.elastic_max_world=1")
                return
            time.sleep(0.01)

    t = threading.Thread(target=refuser)
    t.start()
    with pytest.raises(ElasticError, match="join refused.*max_world"):
        request_join(gen, 3, timeout_s=10)
    t.join(timeout=15)


def test_request_join_times_out_on_dead_generation(tmp_path):
    from tpu_dp.resilience.elastic import request_join

    gen = tmp_path / "gen_dead"
    MembershipLedger(gen, 0).write_initial([0, 1], None)
    with pytest.raises(ElasticError, match="no admission"):
        request_join(gen, 2, timeout_s=0.5, attempts=1)


def test_find_live_generation_picks_newest_by_record_ts(tmp_path):
    from tpu_dp.resilience.elastic import find_live_generation

    assert find_live_generation(tmp_path / "nope") is None
    old = MembershipLedger(tmp_path / "gen_old", 0)
    old.publish_epoch(MembershipRecord(
        epoch=0, members=(0, 1, 2), coordinator=None, ts=100.0))
    new = MembershipLedger(tmp_path / "gen_new", 0)
    new.publish_epoch(MembershipRecord(
        epoch=0, members=(0, 1, 2), coordinator=None, ts=200.0))
    new.publish_epoch(MembershipRecord(
        epoch=1, members=(0, 1), coordinator=None, ts=300.0,
        departed=({"sid": 2, "reason": "preempted (graceful)"},)))
    gen_dir, rec = find_live_generation(tmp_path)
    assert gen_dir.name == "gen_new"
    assert rec.epoch == 1 and rec.members == (0, 1)


# ---------------------------------------------------------------------------
# ledger filesystem IO: bounded, jittered retry (satellite)
# ---------------------------------------------------------------------------


def test_ledger_io_retries_transient_errors(tmp_path, monkeypatch):
    """A transient shared-FS error is a retry, not a spurious failure:
    the first two os.replace calls blow up with EIO, the third lands —
    and the attempts are published to the retry.* obs counters."""
    import tpu_dp.resilience.elastic as elastic_mod
    from tpu_dp.obs.counters import counters

    monkeypatch.setattr(elastic_mod, "_IO_BASE_DELAY_S", 0.001)
    fails = {"n": 2}
    real_replace = os.replace

    def flaky_replace(src, dst):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError(5, "Input/output error (injected)")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", flaky_replace)
    before = counters.get("retry.retries")
    led = MembershipLedger(tmp_path, 0)
    led.check_in(1, 7, leaving=False, flavor="graceful")
    assert led.check_ins(1)[0]["step"] == 7  # the write ultimately landed
    assert counters.get("retry.retries") - before >= 2


def test_ledger_io_exhaustion_raises_typed_error(tmp_path, monkeypatch):
    import tpu_dp.resilience.elastic as elastic_mod
    from tpu_dp.obs.counters import counters

    monkeypatch.setattr(elastic_mod, "_IO_BASE_DELAY_S", 0.001)

    def always_fails(src, dst):
        raise OSError(5, "Input/output error (injected, permanent)")

    monkeypatch.setattr(os, "replace", always_fails)
    before = counters.get("retry.exhausted")
    led = MembershipLedger(tmp_path, 0)
    with pytest.raises(ElasticError, match="failed after .* attempts"):
        led.check_in(1, 7, leaving=False, flavor="graceful")
    assert counters.get("retry.exhausted") - before >= 1


def test_ledger_read_absent_is_answer_not_error(tmp_path):
    # FileNotFoundError is protocol state (record not written yet); the
    # retry layer must pass it through as None immediately.
    led = MembershipLedger(tmp_path, 0)
    assert led.try_plan(4) is None
    assert led.join_request(4, 9) is None


def test_ledger_read_exhaustion_degrades_to_none(tmp_path, monkeypatch):
    """Exhausted READS degrade to "not readable yet" instead of raising:
    every read sits in a protocol poll loop already bounded by
    regroup_timeout_s, so the poll cadence out-retries any in-call
    schedule — a long FS brownout must not kill the rank mid-regroup."""
    import tpu_dp.resilience.elastic as elastic_mod

    monkeypatch.setattr(elastic_mod, "_IO_BASE_DELAY_S", 0.001)
    led = MembershipLedger(tmp_path, 0)
    led.check_in(1, 7, leaving=False, flavor="graceful")

    def always_fails(self, *a, **kw):
        raise OSError(5, "Input/output error (injected, permanent)")

    monkeypatch.setattr(Path, "read_text", always_fails)
    assert led.try_plan(1) is None  # degraded, not raised


def test_faultinject_relaunch_departs_like_leave():
    from tpu_dp.resilience import FaultInjector, FaultPlan

    plan = FaultPlan.parse("relaunch:step=3,rank=1")
    assert (plan.kind, plan.step, plan.rank) == ("relaunch", 3, 1)
    bystander = FaultInjector(plan, rank=0)
    bystander.on_step(9)
    assert not bystander.leave_requested
    target = FaultInjector(plan, rank=1)
    target.on_step(2)
    assert not target.leave_requested
    target.on_step(3)
    # Departs exactly like leave:; `run_elastic` keys the rejoin off the
    # fired plan's kind.
    assert target.leave_requested and target.fired


# ---------------------------------------------------------------------------
# fault injection: the signal-free elastic specs
# ---------------------------------------------------------------------------


def test_faultinject_leave_and_rank_gated_preempt():
    from tpu_dp.resilience import FaultInjector, FaultPlan

    plan = FaultPlan.parse("leave:step=4,rank=2")
    assert (plan.kind, plan.step, plan.rank) == ("leave", 4, 2)
    # Rank-gated: only the targeted rank's injector fires.
    bystander = FaultInjector(plan, rank=0)
    bystander.on_step(9)
    assert not bystander.leave_requested and not bystander.fired
    target = FaultInjector(plan, rank=2)
    target.on_step(3)
    assert not target.leave_requested
    target.on_step(4)
    assert target.leave_requested and target.fired
    # `preempt:rank=R` parses the same gating (the SIGTERM twin).
    p2 = FaultPlan.parse("preempt:rank=2,step=9")
    assert (p2.kind, p2.rank, p2.step) == ("preempt", 2, 9)


# ---------------------------------------------------------------------------
# resume hardening: torn step dirs must not fail the regroup
# ---------------------------------------------------------------------------


def _fake_save(dir_path: Path, payload: bytes = b"x"):
    dir_path.mkdir(parents=True)
    (dir_path / "state.msgpack").write_bytes(payload)
    (dir_path / "meta.json").write_text("{}")


def test_find_latest_skips_partial_step_dir(tmp_path, caplog):
    from tpu_dp.resilience import find_candidates, find_latest

    snaps = tmp_path / "snapshots"
    _fake_save(snaps / "step_0000000010")
    # The crash-mid-snapshot signature: state landed, meta never did.
    torn = snaps / "step_0000000020"
    torn.mkdir(parents=True)
    (torn / "state.msgpack").write_bytes(b"y")
    found = find_latest(tmp_path / "none", snaps)
    assert found is not None and found[0].name == "step_0000000010"
    # ... even when the `latest` pointer names the torn dir.
    (snaps / "latest").write_text("step_0000000020")
    assert find_latest(tmp_path / "none", snaps)[0].name == "step_0000000010"
    assert [d.name for d, _ in find_candidates(tmp_path / "none", snaps)] == [
        "step_0000000010"
    ]


def test_resume_latest_falls_back_past_corrupt_payload(tmp_path, cpu_mesh_1):
    import jax

    from tpu_dp import checkpoint as ckpt_lib
    from tpu_dp.models import Net
    from tpu_dp.resilience import resume_latest
    from tpu_dp.train import SGD, create_train_state

    state = create_train_state(Net(), jax.random.PRNGKey(0),
                               np.zeros((1, 32, 32, 3), np.float32), SGD(0.9))
    snaps = tmp_path / "snapshots"
    ckpt_lib.CheckpointManager(snaps, async_save=False).save(
        state, {"kind": "snapshot", "epoch": 0, "steps_done": 1}, step=5
    )
    # A newer save whose payload was truncated by the dying host — both
    # files exist, so only the msgpack parse can reveal the tear.
    _fake_save(snaps / "step_0000000009", payload=b"\x00truncated")
    restored, meta, source = resume_latest(state, tmp_path / "none", snaps)
    assert source.name == "step_0000000005"
    assert meta["steps_done"] == 1
    with pytest.raises(FileNotFoundError):
        resume_latest(state, tmp_path / "empty")


# ---------------------------------------------------------------------------
# Trainer: single-process departure + resume (the exit-143 contract)
# ---------------------------------------------------------------------------


def _elastic_cfg(tmp_path, **over):
    from tpu_dp.config import Config

    cfg = Config()
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_train_size = 48
    cfg.data.synthetic_test_size = 16
    cfg.data.batch_size = 4
    cfg.train.epochs = 2
    cfg.train.log_every = 100
    cfg.train.eval_at_end = False
    cfg.train.steps_per_call = 1
    cfg.train.ckpt_dir = str(tmp_path / "ck")
    cfg.train.ckpt_async = False
    cfg.parallel.num_devices = 1  # the conftest mesh is 8 virtual devices
    cfg.resilience.elastic = True
    for key, val in over.items():
        cfg.override(key, str(val))
    return cfg


def test_trainer_elastic_requires_drop_remainder(tmp_path):
    from tpu_dp.train.trainer import Trainer

    cfg = _elastic_cfg(tmp_path)
    cfg.data.drop_remainder = False
    with pytest.raises(ValueError, match="drop_remainder"):
        Trainer(cfg)


@pytest.mark.resilience
def test_trainer_leave_fault_departs_with_membership_manifest(tmp_path):
    from tpu_dp.resilience import PreemptedError
    from tpu_dp.train.trainer import Trainer

    cfg = _elastic_cfg(tmp_path, **{"resilience.fault": "leave:step=3"})
    tr = Trainer(cfg)
    with pytest.raises(PreemptedError, match="elastic departure"):
        tr.fit()
    # The ledger recorded the whole transition...
    gen_dirs = list((tmp_path / "ck" / "membership").iterdir())
    assert len(gen_dirs) == 1
    names = {p.name for p in gen_dirs[0].iterdir()}
    assert {"epoch_0000.json", "plan_e0001.json", "left_r00000.json",
            "q_e0001_r00000.json", "q_e0001_r00000.done"} <= names
    plan = json.loads((gen_dirs[0] / "plan_e0001.json").read_text())
    assert plan["leavers"] == [0] and plan["survivors"] == []
    # ... and the final snapshot carries the membership lineage the next
    # incarnation (or a survivor regroup) re-splits from.
    snap_meta = json.loads(
        (Path(tr.snapshot_dir) / f"step_{plan['stop_step']:010d}"
         / "meta.json").read_text()
    )
    assert snap_meta["kind"] == "snapshot"
    assert snap_meta["membership"]["lineage"] == [[1, plan["stop_step"]]]
    assert snap_meta["membership"]["members"] == [0]


@pytest.mark.resilience
def test_trainer_leave_then_auto_resume_bitwise_identical(tmp_path):
    import jax

    from tpu_dp.resilience import PreemptedError
    from tpu_dp.train.trainer import Trainer

    cfg = _elastic_cfg(tmp_path, **{"resilience.fault": "leave:step=3"})
    with pytest.raises(PreemptedError):
        Trainer(cfg).fit()
    resumed = Trainer(_elastic_cfg(tmp_path, **{"train.resume": "true"}))
    resumed.fit()

    ref = Trainer(_elastic_cfg(tmp_path / "ref"))
    ref.fit()
    for a, b in zip(jax.tree_util.tree_leaves(resumed.state),
                    jax.tree_util.tree_leaves(ref.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture
def cpu_mesh_1():
    from tpu_dp.parallel import dist

    return dist.data_mesh(num_devices=1)
