"""Sync-BatchNorm semantics fall out of the sharded program.

`tpu_dp/models/resnet.py` claims BatchNorm batch statistics are computed
over the *global* batch under jit+GSPMD (sync-BN without a wrapper): with
the batch sharded over the data axis, the mean/var reductions become
cross-chip all-reduces. Verify: training a BN model one step on an 8-device
mesh produces the same running stats and params as on a 1-device mesh with
the identical global batch.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import ResNet18
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step


def _copy(state):
    return jax.tree_util.tree_map(jnp.array, state)


def test_batch_stats_match_1_vs_8_devices(mesh8, mesh1):
    model = ResNet18(num_classes=10, num_filters=8)  # tiny, real topology
    opt = SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    assert state.has_batch_stats

    ds = make_synthetic(16, 10, seed=0, name="bn")
    batch = {"image": normalize(ds.images), "label": ds.labels}
    step8 = make_train_step(model, opt, mesh8, constant_lr(0.1))
    step1 = make_train_step(model, opt, mesh1, constant_lr(0.1))
    s8, m8 = step8(_copy(state), batch)
    s1, m1 = step1(_copy(state), batch)

    assert float(m8["loss"]) == float(m1["loss"]) or abs(
        float(m8["loss"]) - float(m1["loss"])
    ) < 1e-5
    # Running statistics identical ⇒ the 8-device BN reduced over the global
    # batch, not per-shard slices.
    for a, b in zip(
        jax.tree_util.tree_leaves(s8.batch_stats),
        jax.tree_util.tree_leaves(s1.batch_stats),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s8.params),
        jax.tree_util.tree_leaves(s1.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
