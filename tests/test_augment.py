"""On-device augmentation tests: shapes, determinism, actual variation."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dp.data.augment import make_augment_fn, random_crop_flip
from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import Net
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step


def test_shapes_and_dtype_preserved():
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(np.random.default_rng(0).normal(
        size=(8, 32, 32, 3)).astype(np.float32))
    out = random_crop_flip(rng, images)
    assert out.shape == images.shape and out.dtype == images.dtype


def test_deterministic_in_seed_and_step():
    aug = make_augment_fn(7)
    images = jnp.ones((4, 32, 32, 3), jnp.float32)
    a = aug(jnp.int32(3), images)
    b = aug(jnp.int32(3), images)
    c = aug(jnp.int32(4), images)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_crop_shifts_and_pads_black():
    # A constant-1 image: any nonzero shift drags padding into view. The
    # pad value is -1 — black in the step's [-1, 1]-normalized pixel space,
    # matching torchvision RandomCrop's zero-pad *before* Normalize.
    aug = make_augment_fn(0)
    images = jnp.ones((64, 32, 32, 3), jnp.float32)
    out = np.asarray(aug(jnp.int32(0), images))
    assert (out == -1).any()  # padding visible on shifted images
    assert (out == 1).sum() > out.size * 0.5  # mostly original content
    # Raw-pixel-space use keeps the zero-pad default.
    raw = np.asarray(random_crop_flip(jax.random.PRNGKey(0), images))
    assert ((raw == 0) | (raw == 1)).all()


def test_augmented_training_still_learns(mesh8):
    model, opt = Net(), SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(
        model, opt, mesh8, constant_lr(0.05), augment_fn=make_augment_fn(1)
    )
    ds = make_synthetic(256, 10, seed=1, name="aug")
    losses = []
    for i in range(12):
        sel = slice((i * 64) % 256, (i * 64) % 256 + 64)
        state, m = step(
            state, {"image": normalize(ds.images[sel]), "label": ds.labels[sel]}
        )
        losses.append(float(m["loss"]))
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def test_augment_with_accum_runs(mesh8):
    model, opt = Net(), SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(
        model, opt, mesh8, constant_lr(0.05), accum_steps=2,
        augment_fn=make_augment_fn(1),
    )
    ds = make_synthetic(32, 10, seed=2, name="aug")
    batch = {
        "image": normalize(ds.images).reshape(2, 16, 32, 32, 3),
        "label": ds.labels.reshape(2, 16),
    }
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"])) and int(m["count"]) == 32
