"""Determinism checks: same seed ⇒ identical params; replicas bitwise equal."""

import jax
import numpy as np

from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import Net
from tpu_dp.parallel.sharding import replicated_sharding, shard_batch
from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step
from tpu_dp.utils.determinism import check_replica_consistency, local_digest


def test_same_seed_same_init():
    model, opt = Net(), SGD(0.9)
    x = np.zeros((1, 32, 32, 3), np.float32)
    a = create_train_state(model, jax.random.PRNGKey(5), x, opt)
    b = create_train_state(model, jax.random.PRNGKey(5), x, opt)
    for la, lb in zip(
        jax.tree_util.tree_leaves(a.params), jax.tree_util.tree_leaves(b.params)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert local_digest(a.params) == local_digest(b.params)


def test_replicas_bitwise_consistent_after_training(mesh8):
    model, opt = Net(), SGD(0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    # Place the state replicated over all 8 devices, step it a few times,
    # then check every device replica is bitwise identical.
    state = jax.device_put(state, replicated_sharding(mesh8))
    step = make_train_step(model, opt, mesh8, constant_lr(0.05))
    ds = make_synthetic(64, 10, seed=0, name="det")
    batch = shard_batch(
        {"image": normalize(ds.images), "label": ds.labels}, mesh8
    )
    for _ in range(3):
        state, _ = step(state, batch)
    assert check_replica_consistency(state.params) == 0.0
    assert check_replica_consistency(state.opt_state) == 0.0


def test_divergent_replicas_detected(mesh8):
    """Negative control: visibly different per-device data is flagged."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # A device-varying array disguised as 'one value per device': each shard
    # covers a (1, 4) slice, so the full-replica filter skips it — build a
    # genuinely replicated array, then corrupt one device's buffer by
    # constructing from distinct per-device arrays.
    devices = list(mesh8.devices.flat)
    shards = [
        jax.device_put(np.full((4,), float(i == 3), np.float32), d)
        for i, d in enumerate(devices)
    ]
    arr = jax.make_array_from_single_device_arrays(
        (4,), NamedSharding(mesh8, P()), shards
    )
    assert check_replica_consistency([arr]) == 1.0
