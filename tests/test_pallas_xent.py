"""Pallas fused cross-entropy vs the jnp reference path (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.ops.xent import mean_softmax_xent, softmax_xent
from tpu_dp.train.step import cross_entropy_loss


@pytest.mark.parametrize("b,c", [(16, 10), (300, 100), (256, 10)])
def test_forward_matches_jnp(b, c):
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(b, c)).astype(np.float32) * 4)
    labels = jnp.asarray(rng.integers(0, c, size=b))
    per_ex = softmax_xent(logits, labels)
    assert per_ex.shape == (b,)
    expected = float(cross_entropy_loss(logits, labels))
    assert float(jnp.mean(per_ex)) == pytest.approx(expected, rel=1e-5)


def test_grad_matches_jnp():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(64, 10)).astype(np.float32) * 3)
    labels = jnp.asarray(rng.integers(0, 10, size=64))

    g_fused = jax.grad(lambda l: jnp.mean(softmax_xent(l, labels)))(logits)
    g_ref = jax.grad(lambda l: cross_entropy_loss(l, labels))(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_weighted_mean_matches_reference():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(32, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=32))
    weight = jnp.asarray((rng.uniform(size=32) > 0.3).astype(np.float32))
    fused = float(mean_softmax_xent(logits, labels, weight))
    ref = float(cross_entropy_loss(logits, labels, weight))
    assert fused == pytest.approx(ref, rel=1e-5)


def test_under_jit_and_nonaligned_batch():
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(37, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=37))
    f = jax.jit(lambda l, y: jnp.mean(softmax_xent(l, y)))
    assert float(f(logits, labels)) == pytest.approx(
        float(cross_entropy_loss(logits, labels)), rel=1e-5
    )
