"""Pallas fused cross-entropy vs the jnp reference path (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.ops.xent import mean_softmax_xent, softmax_xent
from tpu_dp.train.step import cross_entropy_loss


@pytest.mark.parametrize("b,c", [(16, 10), (300, 100), (256, 10)])
def test_forward_matches_jnp(b, c):
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(b, c)).astype(np.float32) * 4)
    labels = jnp.asarray(rng.integers(0, c, size=b))
    per_ex = softmax_xent(logits, labels)
    assert per_ex.shape == (b,)
    expected = float(cross_entropy_loss(logits, labels))
    assert float(jnp.mean(per_ex)) == pytest.approx(expected, rel=1e-5)


def test_grad_matches_jnp():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(64, 10)).astype(np.float32) * 3)
    labels = jnp.asarray(rng.integers(0, 10, size=64))

    g_fused = jax.grad(lambda l: jnp.mean(softmax_xent(l, labels)))(logits)
    g_ref = jax.grad(lambda l: cross_entropy_loss(l, labels))(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_weighted_mean_matches_reference():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(32, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=32))
    weight = jnp.asarray((rng.uniform(size=32) > 0.3).astype(np.float32))
    fused = float(mean_softmax_xent(logits, labels, weight))
    ref = float(cross_entropy_loss(logits, labels, weight))
    assert fused == pytest.approx(ref, rel=1e-5)


def test_under_jit_and_nonaligned_batch():
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(37, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=37))
    f = jax.jit(lambda l, y: jnp.mean(softmax_xent(l, y)))
    assert float(f(logits, labels)) == pytest.approx(
        float(cross_entropy_loss(logits, labels)), rel=1e-5
    )


def test_batch_sharding_propagates_under_mesh(mesh8):
    """GSPMD must shard the kernel's rows over the mesh, not replicate it
    (the regression probe is the output sharding), and values must match
    the unsharded run — forward and backward."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(64, 10)).astype(np.float32) * 3)
    labels = jnp.asarray(rng.integers(0, 10, size=64))
    ls = jax.device_put(logits, NamedSharding(mesh8, P("data")))
    ys = jax.device_put(labels, NamedSharding(mesh8, P("data")))

    f = jax.jit(lambda l, y: softmax_xent(l, y))
    per_ex = f(ls, ys)
    assert per_ex.sharding.spec == P("data")
    np.testing.assert_allclose(np.asarray(per_ex),
                               np.asarray(softmax_xent(logits, labels)),
                               rtol=1e-6)

    g = jax.jit(jax.grad(lambda l, y: jnp.mean(softmax_xent(l, y))))
    gl = g(ls, ys)
    assert gl.sharding.spec[0] == "data"
    np.testing.assert_allclose(
        np.asarray(gl),
        np.asarray(jax.grad(lambda l: jnp.mean(softmax_xent(l, labels)))(
            logits)),
        rtol=1e-5, atol=1e-7)


def test_shard_map_step_with_pallas_xent(mesh8):
    """The explicit-collectives step with the Pallas loss: per-shard kernel
    under shard_map (jnp fallback in interpret mode) must match the GSPMD
    statement of the same program."""
    import numpy as np

    from tpu_dp.data.cifar import make_synthetic, normalize
    from tpu_dp.models import Net
    from tpu_dp.train import (
        SGD, constant_lr, create_train_state, make_train_step,
        make_train_step_shard_map,
    )

    opt = SGD(momentum=0.9)
    ds = make_synthetic(16, 10, seed=0, name="xent_sm")
    batch = {"image": normalize(ds.images), "label": ds.labels}
    x0 = np.zeros((1, 32, 32, 3), np.float32)

    m_sm = Net()
    s_sm = create_train_state(m_sm, jax.random.PRNGKey(0), x0, opt)
    _, met_sm = make_train_step_shard_map(
        m_sm, opt, mesh8, constant_lr(0.1), use_pallas_xent=True)(
        s_sm, dict(batch))

    m_g = Net()
    s_g = create_train_state(m_g, jax.random.PRNGKey(0), x0, opt)
    _, met_g = make_train_step(m_g, opt, mesh8, constant_lr(0.1),
                               use_pallas_xent=True)(s_g, dict(batch))
    assert float(met_sm["loss"]) == pytest.approx(float(met_g["loss"]),
                                                  rel=2e-4)
