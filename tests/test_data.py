"""Data pipeline tests: normalization parity, pipeline sharding, prefetch."""

import jax
import numpy as np
import pytest

from tpu_dp.data import ArrayDataset, DataPipeline, load_dataset
from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.parallel import dist


def test_normalize_matches_reference_transform():
    """ToTensor + Normalize(0.5, 0.5) == x/255*2-1 (`cifar_example.py:38-40`)."""
    u8 = np.array([[0, 127, 255]], dtype=np.uint8)
    out = normalize(u8)
    np.testing.assert_allclose(out, [[-1.0, 127 / 255 * 2 - 1, 1.0]], atol=1e-6)


def test_synthetic_is_deterministic_and_separable():
    a = make_synthetic(100, 10, seed=5, name="s")
    b = make_synthetic(100, 10, seed=5, name="s")
    np.testing.assert_array_equal(a.images, b.images)
    np.testing.assert_array_equal(a.labels, b.labels)
    # Class templates differ: mean images of two classes are far apart.
    m0 = a.images[a.labels == a.labels[0]].mean(axis=0)
    other = a.labels[a.labels != a.labels[0]][0]
    m1 = a.images[a.labels == other].mean(axis=0)
    assert np.abs(m0.astype(np.float32) - m1.astype(np.float32)).mean() > 5


def test_load_dataset_synthetic_fallback(tmp_path):
    ds = load_dataset("cifar10", tmp_path, train=True, synthetic_num_examples=64)
    assert ds.synthetic and len(ds) == 64 and ds.num_classes == 10
    ds100 = load_dataset("cifar100", tmp_path, train=False,
                         synthetic_num_examples=32)
    assert ds100.num_classes == 100


def test_pipeline_shapes_and_epoch(mesh8):
    ds = make_synthetic(100, 10, seed=0, name="s")
    pipe = DataPipeline(ds, batch_size=16, mesh=mesh8, seed=0, prefetch=2)
    assert len(pipe) == 6  # 100 // 16 with drop_remainder
    batches = list(pipe)
    assert len(batches) == 6
    for b in batches:
        assert b["image"].shape == (16, 32, 32, 3)
        assert b["label"].shape == (16,)
        # Default pipeline ships uint8; the compiled step normalizes on
        # device (4x less host->HBM traffic).
        assert b["image"].dtype == np.uint8
        # Sharded over the data axis of the mesh.
        assert b["image"].sharding.spec[0] == dist.DATA_AXIS

    pipe.set_epoch(0)
    first = next(iter(pipe))
    pipe.set_epoch(1)
    second = next(iter(pipe))
    assert not np.allclose(np.asarray(first["image"]), np.asarray(second["image"]))


def test_pipeline_no_prefetch_matches_prefetch(mesh8):
    ds = make_synthetic(64, 10, seed=2, name="s")
    p0 = DataPipeline(ds, 16, mesh8, shuffle=False, prefetch=0)
    p2 = DataPipeline(ds, 16, mesh8, shuffle=False, prefetch=2)
    for a, b in zip(p0, p2):
        np.testing.assert_array_equal(np.asarray(a["image"]), np.asarray(b["image"]))
        np.testing.assert_array_equal(np.asarray(a["label"]), np.asarray(b["label"]))


def test_cifar10_pickle_format_roundtrip(tmp_path):
    """Write the standard CIFAR-10 batch layout and load it back."""
    import pickle

    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        data = rng.integers(0, 256, size=(20, 3072), dtype=np.int64).astype(np.uint8)
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": data, b"labels": list(rng.integers(0, 10, 20))}, f)
    ds = load_dataset("cifar10", tmp_path, train=True)
    assert not ds.synthetic
    assert ds.images.shape == (100, 32, 32, 3)


def test_device_normalize_equals_host_normalize(mesh8):
    """uint8-to-device + in-step normalize ≡ host normalize (same training)."""
    from tpu_dp.models import Net
    from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

    ds = make_synthetic(32, 10, seed=3, name="dn")
    model, opt = Net(), SGD(0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(model, opt, mesh8, constant_lr(0.05))

    def _copy(s):
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.array, s)

    s_u8, m_u8 = step(_copy(state), {"image": ds.images, "label": ds.labels})
    s_f32, m_f32 = step(
        _copy(state), {"image": normalize(ds.images), "label": ds.labels}
    )
    assert float(m_u8["loss"]) == pytest.approx(float(m_f32["loss"]), rel=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_u8.params),
        jax.tree_util.tree_leaves(s_f32.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_partial_batch_weight_mask(mesh8):
    """Eval pipeline: final partial batch is padded with a zeroing mask."""
    ds = make_synthetic(40, 10, seed=4, name="pw")
    pipe = DataPipeline(ds, 32, mesh8, shuffle=False, drop_remainder=False,
                        prefetch=0)
    batches = list(pipe)
    assert len(batches) == 2
    assert "weight" not in batches[0]
    w = np.asarray(batches[1]["weight"])
    assert batches[1]["image"].shape == (32, 32, 32, 3)
    assert w.sum() == 8 and (w[:8] == 1).all() and (w[8:] == 0).all()


def test_partial_batch_pad_exceeding_shard(mesh8):
    """Pad larger than the shard itself must tile the shard (np.resize)."""
    ds = make_synthetic(8, 10, seed=5, name="tiny")
    pipe = DataPipeline(ds, 24, mesh8, shuffle=False, drop_remainder=False,
                        prefetch=0)
    (b,) = list(pipe)
    assert b["image"].shape == (24, 32, 32, 3)
    assert np.asarray(b["weight"]).sum() == 8


def test_accum_requires_drop_remainder(mesh8):
    ds = make_synthetic(64, 10, seed=6, name="ar")
    with pytest.raises(ValueError, match="drop_remainder"):
        DataPipeline(ds, 16, mesh8, accum_steps=2, drop_remainder=False)


def test_pipeline_windows_grouping(mesh8):
    """windows(k): full k-stacks then per-step singles for the remainder."""
    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.data.pipeline import DataPipeline

    ds = make_synthetic(9 * 16, 10, seed=0, name="synthetic")
    pipe = DataPipeline(ds, 16, mesh8, shuffle=False, prefetch=1)
    items = list(pipe.windows(4))
    assert [n for n, _ in items] == [4, 4, 1]
    pool = items[0][1]
    assert pool["image"].shape == (4, 16, 32, 32, 3)
    single = items[2][1]
    assert single["image"].shape == (16, 32, 32, 3)
    # Coverage: stacked + single batches reproduce the plain iteration order.
    import numpy as np

    plain = [np.asarray(b["label"]) for b in pipe]
    windowed = []
    for n, item in items:
        lab = np.asarray(item["label"])
        windowed.extend(lab[j] for j in range(n)) if n > 1 else windowed.append(lab)
    np.testing.assert_array_equal(np.concatenate(plain),
                                  np.concatenate(windowed))

    with pytest.raises(ValueError):
        list(DataPipeline(ds, 16, mesh8, shuffle=False,
                          drop_remainder=False).windows(4))


def test_index_windows_match_windows(mesh8):
    """index_windows(k) names exactly the examples windows(k) ships.

    Gathering the resident dataset with the yielded indices must reproduce
    the streaming windows' labels, window for window — the resident path's
    ordering contract.
    """
    from tpu_dp.data.pipeline import DataPipeline

    ds = make_synthetic(9 * 16, 10, seed=0, name="synthetic")
    pipe = DataPipeline(ds, 16, mesh8, shuffle=True, seed=3, prefetch=0)
    pipe.set_epoch(1)
    streamed = [(n, np.asarray(item["label"]))
                for n, item in pipe.windows(4)]
    pipe.set_epoch(1)  # same epoch permutation for the index pass
    indexed = list(pipe.index_windows(4))

    assert [n for n, _ in indexed] == [n for n, _ in streamed] == [4, 4, 1]
    for (n, labels), (_, idx) in zip(streamed, indexed):
        idx = np.asarray(idx)
        assert idx.dtype == np.int32
        assert idx.shape == (n, 16)
        gathered = ds.labels[idx]
        np.testing.assert_array_equal(
            labels if n > 1 else labels[None], gathered
        )

    with pytest.raises(ValueError):
        DataPipeline(ds, 16, mesh8, shuffle=False,
                     drop_remainder=False).index_windows(4)


def test_index_windows_accum_shape(mesh8):
    from tpu_dp.data.pipeline import DataPipeline

    ds = make_synthetic(128, 10, seed=0, name="synthetic")
    pipe = DataPipeline(ds, 16, mesh8, shuffle=False, prefetch=0,
                        accum_steps=2)
    items = list(pipe.index_windows(2))  # 4 updates → 2 windows of 2
    assert [n for n, _ in items] == [2, 2]
    assert items[0][1].shape == (2, 2, 16)  # (window, accum, batch)
    flat = np.concatenate([np.asarray(i).ravel() for _, i in items])
    np.testing.assert_array_equal(flat, np.arange(128, dtype=np.int32))
