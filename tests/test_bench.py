"""bench.py harness logic — the parts that must work during a TPU outage.

The measurement itself needs an accelerator; what these tests pin down is
the outage machinery: device-kind→peak mapping, the self-archive fallback
(most recent non-cpu result wins; nulls and cpu smoke runs are skipped),
and subprocess output parsing — the round-1 failure mode was a bench that
let a relay outage erase the round's number (VERDICT.md "What's weak" #2).
"""

import json

import bench


def test_peak_flops_known_kinds():
    assert bench.peak_flops("TPU v5 lite") == 197e12
    assert bench.peak_flops("TPU v5e") == 197e12
    assert bench.peak_flops("TPU v5p") == 459e12
    assert bench.peak_flops("TPU v4") == 275e12
    assert bench.peak_flops("TPU v3") == 123e12
    assert bench.peak_flops("TPU v6 lite") == 918e12


def test_peak_flops_v5_lite_not_misread_as_v5p():
    # Substring order matters: "v5 lite" must match before bare "v5".
    assert bench.peak_flops("tpu v5 lite") == 197e12


def test_peak_flops_unknown_is_none():
    assert bench.peak_flops("cpu") is None
    assert bench.peak_flops("Graphcore IPU") is None


# ---- FLOPs resolution (the round-2 30x MFU bug, VERDICT r2 weak #1) ----
# At b2048 the true per-step figure is ~5.97e12 (measured w1 on the real
# chip); the buggy path divided the scan body's cost by the window again
# and published 1.99e11. These tests mock the cost-analysis inputs.

B2048_TRUE = bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE * 2048


def test_resolve_prefers_w1_step_cost():
    # When the loop-free step's cost is available it wins outright — the
    # scanned program's ambiguous number must not even be consulted.
    f, source, check = bench.resolve_flops_per_step(
        program_flops=B2048_TRUE, step_flops=5.97e12, window=30,
        per_chip_batch=2048,
        flops_per_image=bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE)
    assert f == 5.97e12 and source == "w1_step_cost_analysis" and check == "ok"


def test_resolve_scan_body_only_semantics_not_divided():
    # jaxlib reports the scan BODY once: dividing by window again is the
    # round-2 bug. Body reading is log-closer to analytic => keep as-is.
    f, source, check = bench.resolve_flops_per_step(
        program_flops=5.97e12, step_flops=None, window=30, per_chip_batch=2048,
        flops_per_image=bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE)
    assert f == 5.97e12
    assert source == "scan_cost_analysis_body" and check == "ok"


def test_resolve_scan_multiplied_semantics_divided():
    # A jaxlib that DOES multiply by trip count must be divided back down.
    f, source, check = bench.resolve_flops_per_step(
        program_flops=30 * 5.97e12, step_flops=None, window=30,
        per_chip_batch=2048,
        flops_per_image=bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE)
    assert f == 5.97e12
    assert source == "scan_cost_analysis_divided" and check == "ok"


def test_resolve_analytic_fallback():
    f, source, check = bench.resolve_flops_per_step(
        program_flops=None, step_flops=None, window=30, per_chip_batch=1024,
        flops_per_image=bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE)
    assert f == bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE * 1024
    assert source == "analytic" and check == "unverified"


def test_resolve_flags_mismatch_with_analytic():
    # A cost number 30x off analytic (the exact round-2 failure magnitude,
    # had it come from the step path) must be flagged, never silent.
    f, source, check = bench.resolve_flops_per_step(
        program_flops=None, step_flops=5.97e12 / 30, window=1,
        per_chip_batch=2048,
        flops_per_image=bench.RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE)
    assert check.startswith("mismatch:")


def _write_archive(tmp_path, records):
    p = tmp_path / "results.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in records))
    return p


def test_last_good_archived_picks_latest_accelerator_result(tmp_path, monkeypatch):
    p = _write_archive(tmp_path, [
        {"metric": bench.METRIC, "value": 30000.0, "unit": bench.UNIT,
         "vs_baseline": 12.0, "backend": "axon", "ts": "t1"},
        {"metric": bench.METRIC, "value": 1.5, "unit": bench.UNIT,
         "vs_baseline": 0.0, "backend": "cpu", "ts": "t2"},       # cpu smoke
        {"metric": bench.METRIC, "value": None, "unit": bench.UNIT,
         "vs_baseline": None, "error": "timeout", "ts": "t3"},    # failed point
    ])
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    rec = bench.last_good_archived()
    assert rec is not None and rec["value"] == 30000.0 and rec["ts"] == "t1"


def test_last_good_archived_best_of_latest_run(tmp_path, monkeypatch):
    # The fallback must mirror live headline semantics: best point of the
    # MOST RECENT run — not the globally-best stale number, and not the
    # last-written line (a sweep ends with deliberately-slow w=1 points).
    p = _write_archive(tmp_path, [
        {"metric": bench.METRIC, "value": 40000.0, "unit": bench.UNIT,
         "vs_baseline": 16.0, "backend": "axon", "ts": "2026-01-01T00:00:00Z"},
        {"metric": bench.METRIC, "value": 31000.0, "unit": bench.UNIT,
         "vs_baseline": 12.4, "backend": "axon", "ts": "2026-02-01T00:00:00Z",
         "config": {"steps_per_call": 30}},
        {"metric": bench.METRIC, "value": 4000.0, "unit": bench.UNIT,
         "vs_baseline": 1.6, "backend": "axon", "ts": "2026-02-01T00:00:00Z",
         "config": {"steps_per_call": 1}},
    ])
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    rec = bench.last_good_archived()
    assert rec is not None and rec["value"] == 31000.0
    # A stale re-emission must say how many points back it up (1-point
    # archive vs full sweep — VERDICT r2 next-round item 8).
    assert rec["run_n_points"] == 2


def test_metric_for_models():
    assert bench.metric_for("resnet18", 10) == bench.METRIC
    assert (bench.metric_for("resnet50", 100)
            == "cifar100_resnet50_train_images_per_sec_per_chip")
    # Each supported model carries a plausible analytic count (R50 does
    # ~2.3x the conv FLOPs of R18 on CIFAR shapes).
    r18, r50 = bench.MODEL_SPECS["resnet18"][0], bench.MODEL_SPECS["resnet50"][0]
    assert 2.0 < r50 / r18 < 2.7


def test_last_good_archived_filters_by_metric(tmp_path, monkeypatch):
    # An archived ResNet-50 point (its own metric) must never be re-emitted
    # as the ResNet-18 headline, even when it is newer and faster-looking.
    r50_metric = bench.metric_for("resnet50", 100)
    p = _write_archive(tmp_path, [
        {"metric": bench.METRIC, "value": 31000.0, "unit": bench.UNIT,
         "vs_baseline": 12.4, "backend": "tpu", "ts": "2026-01-01T00:00:00Z"},
        {"metric": r50_metric, "value": 99000.0, "unit": bench.UNIT,
         "vs_baseline": None, "backend": "tpu", "ts": "2026-02-01T00:00:00Z"},
    ])
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    rec = bench.last_good_archived()
    assert rec is not None and rec["value"] == 31000.0
    rec50 = bench.last_good_archived(r50_metric)
    assert rec50 is not None and rec50["value"] == 99000.0


def test_last_good_archived_metricless_lines_are_resnet18_only(tmp_path,
                                                               monkeypatch):
    # Pre-multi-model archive lines have no "metric" key and were all
    # implicitly the resnet18 headline: a resnet50 query must skip them.
    p = _write_archive(tmp_path, [
        {"value": 30000.0, "unit": bench.UNIT, "vs_baseline": 12.0,
         "backend": "tpu", "ts": "t1"},
    ])
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    assert bench.last_good_archived()["value"] == 30000.0
    assert bench.last_good_archived(bench.headline_metric("resnet50")) is None


def test_last_good_archived_none_on_missing_or_junk(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "RESULTS_PATH", tmp_path / "absent.jsonl")
    assert bench.last_good_archived() is None
    p = tmp_path / "junk.jsonl"
    p.write_text("not json\n{\"value\": null}\n")
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    assert bench.last_good_archived() is None


def test_archive_appends_with_schema_and_config_hash(tmp_path, monkeypatch):
    from tpu_dp.tune.profile import config_hash

    p = tmp_path / "nested" / "results.jsonl"
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    bench.archive({"a": 1})
    bench.archive({"b": 2, "config": {"bucket_mb": 1.0}})
    rows = [json.loads(x) for x in p.read_text().splitlines()]
    assert [r["a" if "a" in r else "b"] for r in rows] == [1, 2]
    # Every archived row is stamped with the archive schema version and
    # the canonical digest of its own config block, so trial rows, BENCH
    # emissions, and tuned.json profiles join on one key.
    assert [r["schema"] for r in rows] == [bench.ARCHIVE_SCHEMA] * 2
    assert rows[0]["config_hash"] == config_hash({})
    assert rows[1]["config_hash"] == config_hash({"bucket_mb": 1.0})


def test_run_point_reports_child_failure(monkeypatch):
    # A child that dies without emitting JSON must yield a structured error
    # record, not an exception.
    monkeypatch.setattr(
        bench, "_run_sub", lambda argv, t, env=None: (1, "noise\n", "boom")
    )
    rec = bench.run_point({"per_chip_batch": 8}, timeout_s=5)
    assert rec["value"] is None
    assert "rc=1" in rec["error"] and "boom" in rec["error"]


def test_run_point_parses_last_json_line(monkeypatch):
    payload = {"metric": bench.METRIC, "value": 123.0, "unit": bench.UNIT,
               "vs_baseline": 0.05}
    out = "bench: chatter\n" + json.dumps(payload) + "\n"
    monkeypatch.setattr(
        bench, "_run_sub", lambda argv, t, env=None: (0, out, "")
    )
    assert bench.run_point({}, timeout_s=5) == payload


def test_run_point_timeout(monkeypatch):
    monkeypatch.setattr(
        bench, "_run_sub", lambda argv, t, env=None: (124, "", "")
    )
    rec = bench.run_point({}, timeout_s=7)
    assert rec["value"] is None and "timeout" in rec["error"]


def test_archive_tags_cpu_backend_as_smoke(tmp_path, monkeypatch):
    # CPU-backend rows are outage-time harness smoke tests under a TPU
    # metric name; archive() must tag them so archive consumers don't need
    # to know the backend convention (docs/DESIGN.md "Benchmarking
    # honestly"). Accelerator rows must stay untagged.
    p = tmp_path / "results.jsonl"
    monkeypatch.setattr(bench, "RESULTS_PATH", p)
    bench.archive({"value": 9.9, "backend": "cpu"})
    bench.archive({"value": 34000.0, "backend": "tpu"})
    rows = [json.loads(x) for x in p.read_text().splitlines()]
    assert rows[0]["smoke"] is True
    assert "smoke" not in rows[1]


def test_fused_sweep_grid_covers_both_windows(monkeypatch, capsys):
    # The fused-variant verdict must include the headline operating point
    # (w30 scanned windows), not just the w1 dispatch-bound comparison
    # (VERDICT r3 weak #4): 5 variants x 2 windows.
    grids = []

    def fake_run_point(cfg, timeout_s):
        grids.append(cfg)
        return {"value": 1.0, "unit": bench.UNIT, "vs_baseline": 0.0,
                "metric": bench.METRIC, "config": cfg}

    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: (
        {"n_devices": 1, "device_kind": "x", "backend": "tpu"}, None))
    monkeypatch.setattr(bench, "run_point", fake_run_point)
    monkeypatch.setattr(bench, "archive", lambda r: None)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py", "--sweep-fused"])
    bench.main()
    capsys.readouterr()  # swallow the emitted headline line
    pts = {(g["fused_stages"], g["fused_bwd"], g["steps_per_call"])
           for g in grids}
    variants = {("", False), ("0", False), ("all", False),
                ("0", True), ("all", True)}
    assert pts == {(fs, fb, w) for fs, fb in variants for w in (1, 30)}
    assert len(grids) == 10


def test_probe_schedule_exponential_backoff():
    """The probe schedule doubles both the inter-attempt wait and the
    per-attempt timeout, capped — the fix for the BENCH_r01–r05 staleness
    (a rigid 3x75s probe gave up before the relay recovered)."""
    sched = bench.probe_schedule(4, 45.0, 10.0)
    assert sched == [(0.0, 45.0), (10.0, 90.0), (20.0, 180.0), (40.0, 360.0)]
    # Caps hold on long schedules.
    long = bench.probe_schedule(8, 45.0, 10.0)
    assert max(t for _, t in long) == 360.0
    assert max(w for w, _ in long) == 120.0
    # A single attempt probes immediately at the base timeout.
    assert bench.probe_schedule(1, 75.0, 15.0) == [(0.0, 75.0)]


def test_latency_steps_recorded_in_grid(monkeypatch, capsys):
    """--latency-steps flows into every grid point's config, so
    measure_point runs the fenced per-step latency pass (the 'latency'
    p50/p95/p99 block that distinguishes tail from mean regressions —
    docs/OBSERVABILITY.md)."""
    grids = []

    def fake_run_point(cfg, timeout_s):
        grids.append(cfg)
        return {"value": 1.0, "unit": bench.UNIT, "vs_baseline": 0.0,
                "metric": bench.METRIC, "config": cfg}

    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: (
        {"n_devices": 1, "device_kind": "x", "backend": "tpu"}, None))
    monkeypatch.setattr(bench, "run_point", fake_run_point)
    monkeypatch.setattr(bench, "archive", lambda r: None)
    monkeypatch.setattr(bench.sys, "argv",
                        ["bench.py", "--latency-steps", "7"])
    bench.main()
    capsys.readouterr()
    assert grids and all(g["latency_steps"] == 7 for g in grids)


def test_update_sharding_recorded_in_grid(monkeypatch, capsys):
    """--update-sharding flows into every grid point's config (and from
    there into the BENCH json config block via measure_point)."""
    grids = []

    def fake_run_point(cfg, timeout_s):
        grids.append(cfg)
        return {"value": 1.0, "unit": bench.UNIT, "vs_baseline": 0.0,
                "metric": bench.METRIC, "config": cfg}

    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: (
        {"n_devices": 1, "device_kind": "x", "backend": "tpu"}, None))
    monkeypatch.setattr(bench, "run_point", fake_run_point)
    monkeypatch.setattr(bench, "archive", lambda r: None)
    monkeypatch.setattr(bench.sys, "argv",
                        ["bench.py", "--update-sharding", "sharded"])
    bench.main()
    capsys.readouterr()
    assert grids and all(g["update_sharding"] == "sharded" for g in grids)
