"""Training guardrails (tpu_dp/resilience/guard.py + the trainer's
sentinel/hook integration, docs/RESILIENCE.md "Guardrails").

The acceptance properties (ISSUE 8):

1. ``TPU_DP_FAULT=nan:step=K`` + ``guard.action=skip`` → the run completes
   and its final params are BITWISE those of an oracle that never saw the
   poisoned batch (quarantine withholds the update on-device; the sampler
   schedule stays exactly-once).
2. ``spike:`` + ``guard.action=rollback`` → the run rewinds to the newest
   complete snapshot, stamps tombstone/generation records, replays, and
   converges.
3. The policy engine, quarantine ledger, SDC checksum/verdict, and the
   rewind-guard plumbing (heartbeat generations, quarantined-save
   skipping) hold their unit contracts.

The cross-rank SDC eviction lives with the other multi-process suites in
`tests/test_multiprocess.py` (it needs real processes to hold divergent
replicas).
"""

import json
import math
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tpu_dp.resilience.guard import (  # noqa: E402
    DivergedError,
    GuardPolicy,
    QuarantineLog,
    digest_of_sums,
    leaf_paths,
    live_records,
    make_params_checksum,
    robust_stats,
    sdc_verdict,
)

pytestmark = pytest.mark.guard


# ---------------------------------------------------------------------------
# Policy engine
# ---------------------------------------------------------------------------


def _applied(step, loss, gnorm=2.0):
    return {"step": step, "loss": loss, "gnorm": gnorm, "applied": 1}


def test_robust_stats_median_and_mad():
    med, mad = robust_stats([1.0, 2.0, 3.0, 4.0, 100.0])
    assert med == 3.0
    assert mad == pytest.approx(1.4826)
    assert robust_stats([]) == (0.0, 0.0)


def test_policy_spike_detection_arms_after_min_steps():
    pol = GuardPolicy(action="warn", spike_window=16, spike_z=6.0,
                      spike_min_steps=4)
    # Unprimed: even an absurd value passes (no baseline to judge against).
    assert pol.observe([_applied(0, 1e9)]) == []
    pol = GuardPolicy(action="warn", spike_window=16, spike_z=6.0,
                      spike_min_steps=4)
    pol.observe([_applied(i, 1.0 + 0.01 * i) for i in range(6)])
    out = pol.observe([_applied(6, 50.0)])
    assert [t.kind for t in out] == ["spike"]
    assert out[0].action == "record"  # warn never escalates
    assert out[0].field == "loss" and out[0].z > 6


def test_policy_spike_excluded_from_baseline():
    pol = GuardPolicy(action="warn", spike_window=16, spike_z=6.0,
                      spike_min_steps=4)
    pol.observe([_applied(i, 1.0 + 0.01 * i) for i in range(6)])
    # The same outlier repeated must keep triggering — a detector that
    # learns "spikes are normal" is a detector that turns itself off.
    for step in (6, 7, 8):
        out = pol.observe([_applied(step, 50.0)])
        assert [t.kind for t in out] == ["spike"], step


def test_policy_gradnorm_spike_detected():
    pol = GuardPolicy(action="rollback", spike_window=16, spike_z=6.0,
                      spike_min_steps=4)
    pol.observe([_applied(i, 1.0, gnorm=2.0 + 0.01 * i) for i in range(6)])
    out = pol.observe([_applied(6, 1.0, gnorm=500.0)])
    assert [t.field for t in out] == ["grad_norm"]
    assert out[0].action == "rollback"


def test_policy_nonfinite_and_cap_records():
    pol = GuardPolicy(action="skip", spike_window=16, spike_min_steps=4)
    out = pol.observe([
        {"step": 3, "loss": float("nan"), "gnorm": float("nan"),
         "applied": 0},
        {"step": 4, "loss": 2.0, "gnorm": 2.0, "applied": 0},
    ])
    assert [t.kind for t in out] == ["nonfinite", "cap"]
    assert all(t.action == "record" for t in out)


def test_policy_device_cap_arms_only_for_skip():
    records = [_applied(i, 1.0 + 0.01 * i) for i in range(8)]
    skip = GuardPolicy(action="skip", spike_window=16, spike_z=6.0,
                       spike_min_steps=4)
    skip.observe(records)
    assert math.isfinite(skip.loss_cap())
    roll = GuardPolicy(action="rollback", spike_window=16, spike_z=6.0,
                       spike_min_steps=4)
    roll.observe(records)
    assert math.isinf(roll.loss_cap())


def test_policy_rollback_budget_escalates_to_halt():
    pol = GuardPolicy(action="rollback", max_rollbacks=2)
    pol.observe([_applied(0, 1.0)])
    pol.on_rollback()
    pol.on_rollback()
    with pytest.raises(DivergedError, match="without progress"):
        pol.on_rollback()
    # Progress past the high-water step resets the streak.
    pol2 = GuardPolicy(action="rollback", max_rollbacks=2)
    pol2.observe([_applied(0, 1.0)])
    pol2.on_rollback()
    pol2.observe([_applied(5, 1.0)])  # progressed
    pol2.on_rollback()
    pol2.on_rollback()  # streak 2 again, still within budget


def test_policy_rejects_bad_action():
    with pytest.raises(ValueError, match="guard.action"):
        GuardPolicy(action="explode")


# ---------------------------------------------------------------------------
# Quarantine ledger
# ---------------------------------------------------------------------------


def test_quarantine_log_roundtrip_and_tombstones(tmp_path):
    log = QuarantineLog(tmp_path / "q.jsonl")
    log.quarantine(epoch=0, step=4, sample_range=(12, 16), rank=0,
                   reason="nan")
    log.record("spike", step=9, field="loss", value=50.0, z=12.0,
               action="rollback")
    log.tombstone(from_step=9, to_step=5, reason="rollback")
    assert log.generation == 1
    log.quarantine(epoch=0, step=7, sample_range=(24, 28), rank=0,
                   reason="replayed nan")
    recs = log.read()
    assert [r["kind"] for r in recs] == [
        "quarantine", "spike", "tombstone", "quarantine"]
    assert recs[-1]["rollback_generation"] == 1
    # The reader-side sweep: the generation-0 spike at step 9 was undone
    # by the rewind to step 5; the step-4 quarantine predates it and the
    # generation-1 record postdates it — both survive.
    live = live_records(recs)
    assert [(r["kind"], r["step"]) for r in live] == [
        ("quarantine", 4), ("quarantine", 7)]


# ---------------------------------------------------------------------------
# SDC checksum + verdict
# ---------------------------------------------------------------------------


def test_params_checksum_detects_single_bit_flip():
    params = {"conv": {"kernel": np.linspace(-1, 1, 37, dtype=np.float32)
                       .reshape(37)},
              "dense": {"bias": np.zeros(5, np.float32)}}
    checksum = make_params_checksum(params)
    base = np.asarray(checksum(params))
    corrupt = {"conv": {"kernel": params["conv"]["kernel"].copy()},
               "dense": {"bias": params["dense"]["bias"].copy()}}
    view = corrupt["conv"]["kernel"].view(np.uint32)
    view[11] ^= 1  # one mantissa bit
    flipped = np.asarray(checksum(corrupt))
    assert (base != flipped).any()
    assert digest_of_sums(base) != digest_of_sums(flipped)
    paths = leaf_paths(params)
    assert paths == ["conv/kernel", "dense/bias"]
    # Attribution: only the corrupted leaf's sum moved.
    diff = np.nonzero(base != flipped)[0]
    assert [paths[i] for i in diff] == ["conv/kernel"]


def test_checksum_covers_bf16_and_int_leaves():
    import jax.numpy as jnp

    params = {"w": jnp.ones((4, 3), jnp.bfloat16), "n": jnp.arange(5)}
    sums = np.asarray(make_params_checksum(params)(params))
    assert sums.shape == (2,) and sums.dtype == np.uint32


def test_sdc_verdict_majority_and_split():
    sums = np.array([[1, 2], [1, 2], [9, 2]], np.uint32)
    v = sdc_verdict(sums, ["a", "b"])
    assert not v["consistent"] and v["suspects"] == [2]
    assert v["leaves"] == {2: ["a"]}
    ok = sdc_verdict(np.array([[1, 2], [1, 2]], np.uint32), ["a", "b"])
    assert ok["consistent"] and ok["suspects"] == []
    split = sdc_verdict(np.array([[1, 2], [9, 2]], np.uint32), ["a", "b"])
    assert not split["consistent"]
    assert split["majority"] is None and split["suspects"] == [0, 1]


# ---------------------------------------------------------------------------
# Rewind-guard plumbing: heartbeats + quarantined saves
# ---------------------------------------------------------------------------


def test_heartbeat_rewind_unthrottles_and_scan_dedups(tmp_path):
    from tpu_dp.obs.health import HealthMonitor, HeartbeatWriter

    with HeartbeatWriter(tmp_path, rank=0) as hb:
        for step in (1, 2, 3):
            assert hb.beat(step, 10.0)
        # Rewound below the high-water mark: without rewind() these would
        # all be throttled away and the monitor would read a hang.
        assert not hb.beat(2, 10.0)
        hb.rewind(1)
        assert hb.beat(2, 99.0) and hb.beat(3, 10.0)
    with HeartbeatWriter(tmp_path, rank=1) as hb2:
        for step in (1, 2, 3):
            hb2.beat(step, 10.0)
    mon = HealthMonitor(tmp_path, world=2, straggler_factor=3.0,
                        min_step_ms=1.0)
    by_step = {}
    for rank, beats in mon.read_beats().items():
        for b in beats:
            by_step.setdefault(b["step"], {}).setdefault(rank, 0)
            by_step[b["step"]][rank] += 1
    # Raw file holds the replay duplicates...
    assert by_step[2][0] == 2
    # ...but scan() attributes each (rank, step) once, and prefers the
    # replay (gen 1): rank 0's step-2 time is the replayed 99ms, which is
    # > 3x rank 1's 10ms median — exactly one straggler finding.
    issues = mon.scan()
    flagged = [(i.kind, i.rank, i.step) for i in issues]
    assert flagged == [("straggler", 0, 2)]


def test_find_candidates_skips_quarantined_saves(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib
    from tpu_dp.resilience import find_candidates, quarantine_save_dir

    snap = tmp_path / "snaps"
    for step in (5, 10):
        d = snap / f"step_{step:010d}"
        d.mkdir(parents=True)
        (d / ckpt_lib._CKPT_NAME).write_bytes(b"x")
        (d / ckpt_lib._META_NAME).write_text("{}")
    found = find_candidates(tmp_path / "ck", snap)
    assert [s for _, s in found] == [10, 5]
    quarantine_save_dir(snap / "step_0000000010", "sdc mismatch")
    found = find_candidates(tmp_path / "ck", snap)
    assert [s for _, s in found] == [5]
    # A fresh complete save into the dir supersedes the suspicion: the
    # post-rollback replay re-saves CLEAN state into the same step dirs,
    # and a surviving marker would distrust it forever.
    ckpt_lib._atomic_write_state(
        snap / "step_0000000010", {"x": np.zeros(1, np.float32)},
        {"kind": "snapshot"},
    )
    found = find_candidates(tmp_path / "ck", snap)
    assert [s for _, s in found] == [10, 5]


# ---------------------------------------------------------------------------
# Trainer integration: the acceptance runs
# ---------------------------------------------------------------------------


def _guard_cfg(tmp_path, **over):
    from tpu_dp.config import Config

    cfg = Config()
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_train_size = 48
    cfg.data.synthetic_test_size = 16
    cfg.data.batch_size = 4
    cfg.data.device_resident = "off"
    cfg.train.epochs = 1
    cfg.train.log_every = 1000
    cfg.train.eval_at_end = False
    cfg.train.steps_per_call = 1
    cfg.train.ckpt_dir = str(tmp_path / "ck")
    cfg.train.ckpt_async = False
    cfg.parallel.num_devices = 1
    cfg.guard.enabled = True
    for key, val in over.items():
        cfg.override(key, str(val))
    return cfg


def _oracle_params_skipping(cfg, skip_batches=(), extra_epochs=None):
    """Final params of a run over the same deterministic batch stream that
    never saw the batches in ``skip_batches`` (global batch indices).

    Drives the plain (non-sentinel) `make_train_step` directly: the
    sentinel's disarmed seam and lr_scale=1.0 are multiply-by-1.0 bitwise
    identities, so the two programs must agree bit-for-bit.
    """
    from tpu_dp.config import Config
    from tpu_dp.data.cifar import load_dataset
    from tpu_dp.data.pipeline import DataPipeline
    from tpu_dp.models import build_model
    from tpu_dp.parallel import dist
    from tpu_dp.train.optim import SGD
    from tpu_dp.train.schedule import make_schedule
    from tpu_dp.train.state import create_train_state
    from tpu_dp.train.step import make_train_step

    defaults: Config = cfg
    ds = load_dataset("synthetic", defaults.data.root, train=True,
                      allow_synthetic=True,
                      synthetic_num_examples=defaults.data.synthetic_train_size,
                      seed=defaults.train.seed)
    mesh = dist.data_mesh(num_devices=1)
    model = build_model("net")
    opt = SGD(defaults.optim.momentum, defaults.optim.weight_decay)
    pipe = DataPipeline(ds, defaults.data.batch_size, mesh, shuffle=True,
                        seed=defaults.train.seed, drop_remainder=True,
                        prefetch=defaults.data.prefetch)
    epochs = defaults.train.epochs if extra_epochs is None else extra_epochs
    sched = make_schedule(defaults.optim.schedule, defaults.optim.lr,
                          len(pipe) * epochs, 0, defaults.optim.final_lr)
    state = create_train_state(model, jax.random.PRNGKey(defaults.train.seed),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    step = make_train_step(model, opt, mesh, sched)
    k = 0
    for epoch in range(epochs):
        pipe.set_epoch(epoch)
        for _, item in pipe.windows(1):
            if k not in skip_batches:
                state, _ = step(state, item)
            k += 1
    return state


@pytest.mark.resilience
def test_nan_skip_matches_never_saw_batch_oracle(tmp_path):
    """ISSUE 8 acceptance: nan:step=3 + action=skip completes with final
    params bitwise-identical to an oracle that never trained on batch 3 —
    the quarantined update was withheld on-device (step counter frozen),
    so every later update replays the oracle's trajectory exactly."""
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{"resilience.fault": "nan:step=3",
                                  "guard.action": "skip"})
    tr = Trainer(cfg)
    tr.fit()
    assert int(np.asarray(tr.state.step)) == 11  # 12 batches, 1 skipped

    oracle = _oracle_params_skipping(cfg, skip_batches={3})
    for a, b in zip(jax.tree_util.tree_leaves(tr.state.params),
                    jax.tree_util.tree_leaves(oracle.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    recs = [json.loads(line)
            for line in (tmp_path / "ck" / "quarantine.jsonl").read_text()
            .splitlines()]
    quarantined = [r for r in recs if r["kind"] == "quarantine"]
    assert len(quarantined) == 1
    q = quarantined[0]
    # The record carries (epoch, step, sample-id range, rank): batch 3 is
    # epoch positions [12, 16) of the deterministic shuffle.
    assert q["epoch"] == 0 and q["rank"] == 0
    assert q["step"] == 4  # host step clock: boundary after the 4th batch
    assert q["sample_range"] == [12, 16]
    assert "non-finite" in q["reason"]


@pytest.mark.resilience
def test_guard_off_run_unaffected_by_guard_code(tmp_path):
    """guard.enabled=false trains bitwise-identically to the pre-guardrail
    trainer (same factories, no guard_in, no hook-fetch syncs) — proven
    against the plain-factory oracle."""
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path)
    cfg.guard.enabled = False
    tr = Trainer(cfg)
    tr.fit()
    oracle = _oracle_params_skipping(cfg)
    for a, b in zip(jax.tree_util.tree_leaves(tr.state.params),
                    jax.tree_util.tree_leaves(oracle.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.resilience
def test_sentinel_on_clean_run_bitwise_equals_plain(tmp_path):
    """The sentinel itself is a bitwise no-op on a healthy run: guard on,
    nothing triggering — final params equal the plain factory's (the
    disarmed seam and neutral guard_in are exact identities)."""
    from tpu_dp.train.trainer import Trainer

    tr = Trainer(_guard_cfg(tmp_path))
    tr.fit()
    oracle = _oracle_params_skipping(tr.cfg)
    for a, b in zip(jax.tree_util.tree_leaves(tr.state.params),
                    jax.tree_util.tree_leaves(oracle.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.resilience
def test_spike_rollback_resumes_from_snapshot_and_converges(tmp_path):
    """ISSUE 8 acceptance: spike: + action=rollback rewinds to the newest
    snapshot (tombstoning the rolled-back records), replays clean, and
    the quarantine/rollback events land in metrics + quarantine.jsonl."""
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{
        "resilience.fault": "spike:step=8,scale=1e6",
        "resilience.snapshot_every_steps": "5",
        "guard.action": "rollback",
        "guard.spike_min_steps": "4",
        "guard.spike_window": "16",
        "guard.spike_z": "12",
        "train.epochs": "2",
    })
    tr = Trainer(cfg)
    tr.fit()
    # The run completed both epochs despite the poisoned step.
    assert int(np.asarray(tr.state.step)) == 24
    assert tr._rollback_gen >= 1

    metrics = [json.loads(line) for line in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    rollbacks = [m for m in metrics if m.get("event") == "guard_rollback"]
    assert len(rollbacks) == 1
    # Spike fires at device step 8 (host boundary 9); newest snapshot is 5.
    assert rollbacks[0]["from_step"] == 9
    assert rollbacks[0]["to_step"] == 5
    assert rollbacks[0]["rollback_generation"] == 1
    # Post-rollback records are stamped with the bumped generation.
    later = [m for m in metrics
             if m.get("step", 0) > 9 and "epoch" in m]
    assert all(m.get("rollback_generation") == 1 for m in later)

    recs = [json.loads(line)
            for line in (tmp_path / "ck" / "quarantine.jsonl").read_text()
            .splitlines()]
    kinds = [r["kind"] for r in recs]
    assert "spike" in kinds and "tombstone" in kinds
    tomb = next(r for r in recs if r["kind"] == "tombstone")
    assert tomb["from_step"] == 9 and tomb["to_step"] == 5
    # The reader-side sweep agrees: the rolled-back spike record is dead.
    assert all(r["kind"] != "spike" for r in live_records(recs))

    # Replay converged: the rolled-back pass's snapshot dirs were
    # overwritten by the replay (same step names), and the final epoch
    # trained to a finite loss.
    ep2 = [m for m in metrics if m.get("epoch") == 2]
    assert ep2 and math.isfinite(ep2[-1]["loss"])


@pytest.mark.resilience
def test_nonfinite_halt_raises_diverged_error(tmp_path):
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{"resilience.fault": "nan:step=3",
                                  "guard.action": "halt"})
    tr = Trainer(cfg)
    with pytest.raises(DivergedError, match="non-finite"):
        tr.fit()
    assert DivergedError.exit_code == 65  # EX_DATAERR, never 143/137


def test_nan_fault_requires_guard_enabled(tmp_path):
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{"resilience.fault": "nan:step=3"})
    cfg.guard.enabled = False
    with pytest.raises(ValueError, match="guard.enabled"):
        Trainer(cfg)


@pytest.mark.resilience
def test_on_snapshot_hook_point_fires_for_registered_hooks(tmp_path):
    """Every snapshot commit (cadence here; preemption/quiesce finals go
    through the same `_take_snapshot`) sweeps the registered hooks'
    ``on_snapshot`` — the extension seam external subsystems plug into."""
    from tpu_dp.train.hooks import StepHook
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{"resilience.snapshot_every_steps": "5"})
    tr = Trainer(cfg)
    seen = []

    class Probe(StepHook):
        def on_snapshot(self, epoch, done, step, meta):
            seen.append((step, meta.get("kind")))

    tr._hooks.append(Probe(tr))
    tr.fit()
    assert [s for s, _ in seen] == [5, 10]  # 12 steps at cadence 5
    assert all(kind == "snapshot" for _, kind in seen)


@pytest.mark.resilience
def test_guard_rollback_rearms_cadence_markers(tmp_path):
    """The rewind re-arms every crossing-marker cadence — snapshots,
    heartbeats, the SDC audit, and (elastic) the ledger poll — so the
    replay window is covered, not silently skipped (the markers would
    otherwise sit at the pre-rollback high-water step)."""
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{
        "resilience.fault": "spike:step=8,scale=1e6",
        "resilience.snapshot_every_steps": "3",
        "guard.action": "rollback",
        "guard.spike_min_steps": "4",
        "guard.spike_window": "16",
        "guard.spike_z": "12",
        "guard.sdc_every_steps": "4",
    })
    from tpu_dp.obs.counters import counters

    audits_before = counters.get("guard.sdc_audits")
    tr = Trainer(cfg)
    tr.fit()
    assert tr._rollback_gen == 1
    # The replayed stretch (steps 7..12 after rewinding to the step-6
    # snapshot) was snapshotted again: step_9 exists and postdates the
    # rewind (rollback_generation stamped in its manifest).
    snaps = sorted(p.name for p in Path(tr.snapshot_dir).glob("step_*"))
    assert "step_0000000009" in snaps
    meta = json.loads((Path(tr.snapshot_dir) / "step_0000000009" /
                       "meta.json").read_text())
    assert meta.get("rollback_generation") == 1
    # The audit cadence kept firing through the replay: 12 steps at
    # cadence 4 with one rewind to step 6 crosses at 4, 8, (rewind), 8, 12.
    assert counters.get("guard.sdc_audits") - audits_before == 4


@pytest.mark.resilience
def test_sdc_fault_flips_exactly_one_leaf(tmp_path):
    """The sdc: injection mutates exactly the glob-matched leaf on the
    local replica (single process: the audit stack of one stays trivially
    consistent — cross-rank detection is `tests/test_multiprocess.py`)."""
    from tpu_dp.train.trainer import Trainer

    cfg = _guard_cfg(tmp_path, **{
        "resilience.fault": "sdc:step=3,rank=0,leaf=*conv1*kernel*",
        "guard.sdc_every_steps": "4",
        "guard.sdc_action": "warn",
    })
    tr = Trainer(cfg)
    checksum = make_params_checksum(tr.state.params)
    paths = leaf_paths(tr.state.params)
    target = [i for i, p in enumerate(paths) if "conv1" in p and "kernel" in p]
    assert len(target) == 1
    before = np.asarray(checksum(tr.state.params))
    tr.fit()
    after = np.asarray(checksum(tr.state.params))
    # Training moved everything; the point is the run survived the flip
    # and the audit ran (consistent at world 1).
    assert (before != after).any()
    from tpu_dp.obs.counters import counters

    assert counters.get("guard.sdc_audits") >= 1
