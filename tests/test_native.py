"""Native (C++) host library tests — topology + TCP ring allreduce.

SURVEY.md §4 "Multi-process without a cluster": N local processes over a
loopback rendezvous, the analogue of the reference's `127.0.0.1:29500`
TCPStore (`cifar_example_ddp.py:55-56`). The ring must be semantically
identical to the XLA collective path: allreduce(sum/mean) + barrier
(SURVEY.md §7 hard part (c)).
"""

import multiprocessing as mp
import pickle
import traceback

import numpy as np
import pytest

from tpu_dp.ops.native import available, cpu_count, hostname

pytestmark = pytest.mark.skipif(
    not available(), reason="native host library failed to build"
)


def test_topology_introspection():
    assert cpu_count() >= 1
    assert isinstance(hostname(), str) and hostname()


def _ring_worker(rank, world, base_port, conn):
    try:
        from tpu_dp.ops.native.hostlib import Ring

        rng = np.random.default_rng(rank)
        data = rng.normal(size=257).astype(np.float32)  # odd size: uneven chunks
        with Ring("127.0.0.1", base_port, rank, world, timeout_ms=20_000) as ring:
            summed = ring.allreduce(data.copy(), op="sum")
            meaned = ring.allreduce(data.copy(), op="mean")
            ring.barrier()
        conn.send(pickle.dumps((rank, data, summed, meaned)))
    except BaseException:  # surface the failure to the parent
        conn.send(pickle.dumps(("__error__", traceback.format_exc())))
    finally:
        conn.close()


@pytest.mark.parametrize("world", [2, 3, 4, 5])
def test_ring_allreduce_multiprocess(world):
    ctx = mp.get_context("spawn")
    base_port = 23450 + world * 16
    pipes, procs = [], []
    for rank in range(world):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_ring_worker, args=(rank, world, base_port, child)
        )
        p.start()
        pipes.append(parent)
        procs.append(p)
    results = []
    for parent, p in zip(pipes, procs):
        payload = pickle.loads(parent.recv())
        p.join(timeout=30)
        if isinstance(payload, tuple) and payload[0] == "__error__":
            pytest.fail(f"worker failed:\n{payload[1]}")
        results.append(payload)

    expected_sum = np.sum([r[1] for r in results], axis=0)
    for rank, _, summed, meaned in results:
        np.testing.assert_allclose(summed, expected_sum, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            meaned, expected_sum / world, rtol=1e-5, atol=1e-5
        )


def _bcast_gather_worker(rank, world, base_port, conn):
    try:
        from tpu_dp.ops.native.hostlib import Ring

        with Ring("127.0.0.1", base_port, rank, world, timeout_ms=20_000) as ring:
            # Broadcast: >1 pipeline chunk (256 KiB) to exercise the
            # store-and-forward overlap; int64 to prove byte-typed transport.
            payload = (
                np.arange(100_003, dtype=np.int64)
                if rank == 1
                else np.zeros(100_003, dtype=np.int64)
            )
            bcast = ring.broadcast(payload, root=1)
            gathered = ring.allgather(
                np.full((3, 5), float(rank), dtype=np.float32)
            )
            ring.barrier()
        conn.send(pickle.dumps((rank, bcast, gathered)))
    except BaseException:
        conn.send(pickle.dumps(("__error__", traceback.format_exc())))
    finally:
        conn.close()


@pytest.mark.parametrize("world", [2, 3, 4, 5])
def test_ring_broadcast_allgather_multiprocess(world):
    ctx = mp.get_context("spawn")
    base_port = 23700 + world * 16
    pipes, procs = [], []
    for rank in range(world):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_bcast_gather_worker, args=(rank, world, base_port, child)
        )
        p.start()
        pipes.append(parent)
        procs.append(p)
    expected_bcast = np.arange(100_003, dtype=np.int64)
    expected_gather = np.stack(
        [np.full((3, 5), float(r), dtype=np.float32) for r in range(world)]
    )
    for parent, p in zip(pipes, procs):
        payload = pickle.loads(parent.recv())
        p.join(timeout=30)
        if isinstance(payload, tuple) and payload[0] == "__error__":
            pytest.fail(f"worker failed:\n{payload[1]}")
        _, bcast, gathered = payload
        np.testing.assert_array_equal(bcast, expected_bcast)
        np.testing.assert_array_equal(gathered, expected_gather)


def _primitive_worker(rank, world, base_port, conn):
    try:
        from tpu_dp.ops.native.hostlib import Ring

        rng = np.random.default_rng(100 + rank)
        # >1 pipeline chunk (65536 floats) so reduce exercises the chunked path.
        contrib = rng.normal(size=70_001).astype(np.float32)
        rs_in = np.stack(
            [np.full(37, 10.0 * rank + seg, np.float32) for seg in range(world)]
        )
        rs_in_orig = rs_in.copy()
        with Ring("127.0.0.1", base_port, rank, world, timeout_ms=20_000) as ring:
            reduced = ring.reduce(contrib.copy(), root=1, op="sum")
            seg = ring.reduce_scatter(rs_in, op="sum")
            assert np.array_equal(rs_in, rs_in_orig), "sendbuf must stay const"
            # p2p: everyone sends its rank id forward, receives prev's.
            # (Small payload — symmetric ungrouped send/recv is rendezvous-
            # blocking beyond socket buffering; large symmetric exchanges
            # go through ring.exchange below.)
            ring.send_next(np.array([rank], np.int32))
            from_prev = ring.recv_prev((1,), np.int32)
            shifted = ring.shift(np.array([float(rank)], np.float32), k=1)
            # Grouped sendrecv at 4 MB/rank: overlapped, must not deadlock.
            big = np.full(1_000_000, float(rank), np.float32)
            exchanged = ring.exchange(big)
            assert big[0] == float(rank), "exchange must not clobber input"
            assert np.all(exchanged == float((rank - 1) % world))
            ring.barrier()
        conn.send(pickle.dumps((rank, contrib, reduced, seg, from_prev, shifted)))
    except BaseException:
        conn.send(pickle.dumps(("__error__", traceback.format_exc())))
    finally:
        conn.close()


@pytest.mark.parametrize("world", [2, 3, 4, 5])
def test_ring_reduce_scatter_p2p_shift_multiprocess(world):
    """NCCL primitive-set parity: reduce, reduce-scatter, send/recv, permute."""
    ctx = mp.get_context("spawn")
    base_port = 24100 + world * 16
    pipes, procs = [], []
    for rank in range(world):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_primitive_worker, args=(rank, world, base_port, child)
        )
        p.start()
        pipes.append(parent)
        procs.append(p)
    results = []
    for parent, p in zip(pipes, procs):
        # Bounded wait: these primitives are the rendezvous-deadlock-prone
        # ones — a regression must fail in 2 min, not hang CI.
        if not parent.poll(120):
            for q in procs:
                q.terminate()
            pytest.fail("p2p worker deadlocked (no result within 120s)")
        payload = pickle.loads(parent.recv())
        p.join(timeout=30)
        if isinstance(payload, tuple) and payload[0] == "__error__":
            pytest.fail(f"worker failed:\n{payload[1]}")
        results.append(payload)

    total = np.sum([r[1] for r in results], axis=0)
    for rank, contrib, reduced, seg, from_prev, shifted in results:
        if rank == 1:  # root holds the reduction...
            np.testing.assert_allclose(reduced, total, rtol=1e-5, atol=1e-4)
        else:  # ...everyone else keeps their input (ncclReduce semantics)
            np.testing.assert_array_equal(reduced, contrib)
        # reduce_scatter: rank r's segment = sum over ranks of (10*r' + r)
        expected_seg = np.full(37, sum(10.0 * r + rank for r in range(world)))
        np.testing.assert_allclose(seg, expected_seg, rtol=1e-6)
        assert from_prev[0] == (rank - 1) % world
        assert shifted[0] == float((rank - 1) % world)


def _big_allreduce_worker(rank, world, base_port, conn):
    try:
        from tpu_dp.ops.native.hostlib import Ring

        # 4.2M+1 floats ≈ 16.8 MB: hundreds of pipeline chunks, far past any
        # socket buffer, with an odd element count so every chunk boundary
        # path runs under contention (VERDICT r2 weak #4: the hand-written
        # C++ ring had never been driven past 4 MB).
        n = 4_200_001
        data = np.full(n, float(rank + 1), np.float32)
        with Ring("127.0.0.1", base_port, rank, world,
                  timeout_ms=60_000) as ring:
            out = ring.allreduce(data, op="sum")
            ring.barrier()
        expected = float(sum(r + 1 for r in range(world)))
        # Digest, not the 16 MB array, goes back through the pipe.
        conn.send(pickle.dumps((rank, bool(np.all(out == expected)),
                                float(out.min()), float(out.max()), out.shape)))
    except BaseException:
        conn.send(pickle.dumps(("__error__", traceback.format_exc())))
    finally:
        conn.close()


def test_ring_allreduce_16mb():
    world = 3
    ctx = mp.get_context("spawn")
    base_port = 24600
    pipes, procs = [], []
    for rank in range(world):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_big_allreduce_worker, args=(rank, world, base_port, child)
        )
        p.start()
        pipes.append(parent)
        procs.append(p)
    for rank, (parent, p) in enumerate(zip(pipes, procs)):
        if not parent.poll(120):
            for q in procs:
                q.terminate()
            pytest.fail("16MB allreduce hung (no result within 120s)")
        payload = pickle.loads(parent.recv())
        p.join(timeout=30)
        if isinstance(payload, tuple) and payload[0] == "__error__":
            pytest.fail(f"worker failed:\n{payload[1]}")
        _, all_equal, lo, hi, shape = payload
        assert shape == (4_200_001,)
        assert all_equal, f"rank {rank}: values in [{lo}, {hi}], expected 6.0"


def test_ring_world_one_is_identity():
    from tpu_dp.ops.native.hostlib import Ring

    data = np.arange(5, dtype=np.float32)
    with Ring("127.0.0.1", 23900, 0, 1) as ring:
        out = ring.allreduce(data.copy(), op="mean")
        bcast = ring.broadcast(data.copy())
        gathered = ring.allgather(data)
        seg = ring.reduce_scatter(data[None], op="sum")
        reduced = ring.reduce(data.copy(), root=0, op="sum")
        shifted = ring.shift(data.copy(), k=1)
        # self-loop p2p: send_next pairs with our own recv_prev
        ring.send_next(data)
        echoed = ring.recv_prev(data.shape, data.dtype)
        with pytest.raises(RuntimeError):
            ring.recv_prev(data.shape, data.dtype)  # nothing queued
        ring.barrier()
    np.testing.assert_array_equal(out, data)
    np.testing.assert_array_equal(bcast, data)
    np.testing.assert_array_equal(gathered, data[None])
    np.testing.assert_array_equal(seg, data)
    np.testing.assert_array_equal(reduced, data)
    np.testing.assert_array_equal(shifted, data)
    np.testing.assert_array_equal(echoed, data)


def _dying_peer_worker(rank, world, base_port, conn):
    try:
        import os

        from tpu_dp.ops.native.hostlib import Ring

        ring = Ring("127.0.0.1", base_port, rank, world, timeout_ms=20_000)
        if rank == 1:
            # Die mid-collective without closing cleanly: peers must see a
            # socket error from read/write, not hang.
            conn.send(pickle.dumps((rank, "dying")))
            conn.close()
            os._exit(1)
        try:
            ring.allreduce(np.ones(300_000, np.float32))
            outcome = "no-error"
        except RuntimeError:
            outcome = "raised"
        conn.send(pickle.dumps((rank, outcome)))
    except BaseException:
        conn.send(pickle.dumps(("__error__", traceback.format_exc())))
    finally:
        try:
            conn.close()
        except OSError:
            pass


def test_ring_peer_death_raises_not_hangs():
    """Failure detection: a dead rank fails surviving ranks' collectives fast.

    The reference has no failure handling at all (SURVEY.md §5); here a
    peer's death mid-allreduce must surface as RuntimeError on the
    survivors within the test timeout — never a silent hang (NCCL's analogue
    is the watchdog abort).
    """
    world = 3
    ctx = mp.get_context("spawn")
    base_port = 24400
    pipes, procs = [], []
    for rank in range(world):
        parent, child = ctx.Pipe()
        p = ctx.Process(
            target=_dying_peer_worker, args=(rank, world, base_port, child)
        )
        p.start()
        pipes.append(parent)
        procs.append(p)
    outcomes = {}
    for rank, (parent, p) in enumerate(zip(pipes, procs)):
        if not parent.poll(60):
            for q in procs:
                q.terminate()
            pytest.fail(f"rank {rank} hung after peer death (no failure detection)")
        payload = pickle.loads(parent.recv())
        p.join(timeout=30)
        if isinstance(payload, tuple) and payload[0] == "__error__":
            pytest.fail(f"worker failed:\n{payload[1]}")
        outcomes[payload[0]] = payload[1]
    assert outcomes[1] == "dying"
    assert outcomes[0] == "raised"
    assert outcomes[2] == "raised"
