#!/usr/bin/env python
"""The reference's tutorial, re-told TPU-native — in one page.

This script mirrors the *shape* of `/root/reference/cifar_example_ddp.py`
(init → data → model → train loop → save → synced eval) so a reader of the
reference can see each piece's equivalent, but drives the tpu_dp library
directly instead of `train.py`'s Trainer. The differences ARE the tutorial:

- no launcher fork: the same script is single-chip or a full slice — the
  mesh is however many devices are visible (reference needs `torchrun` and
  a separate non-DDP script);
- no DDP wrapper, no gradient hooks: the whole hot loop
  (`cifar_example_ddp.py:94-107`) is ONE compiled XLA program whose
  cross-chip gradient all-reduce GSPMD inserts from shardings;
- no DistributedSampler object: the pipeline shards per-process and
  reshuffles per epoch (`set_epoch` semantics) internally;
- eval counts are exact global values out of the compiled step — what
  `torchmetrics.Accuracy(dist_sync_on_step=True)` approximates with a
  per-update allreduce (`cifar_example_ddp.py:124-136`).

Run: `python examples/cifar_minimal.py` (synthetic data if no CIFAR on disk;
CPU works — on a TPU host the same command uses every chip).
"""

import jax
import numpy as np

from tpu_dp.checkpoint import save_params
from tpu_dp.data.cifar import load_dataset
from tpu_dp.data.pipeline import DataPipeline
from tpu_dp.models import Net
from tpu_dp.parallel import dist
from tpu_dp.train import SGD, constant_lr, create_train_state, make_eval_step, make_train_step
from tpu_dp.utils import print0

EPOCHS = 2          # cifar_example.py:66
BATCH = 4           # per-process, cifar_example.py:46
LR, MOMENTUM = 0.001, 0.9  # cifar_example.py:64
LOG_EVERY = 2000    # cifar_example.py:84


def main():
    dist.initialize()                      # ≙ init_distributed (ddp.py:42-58)
    mesh = dist.data_mesh()                # the world; 1 chip or 8, same code

    train_ds = load_dataset("cifar10", "./data", train=True)
    test_ds = load_dataset("cifar10", "./data", train=False)
    train_pipe = DataPipeline(train_ds, BATCH, mesh, shuffle=True)
    test_pipe = DataPipeline(test_ds, BATCH, mesh, shuffle=False,
                             drop_remainder=False)

    model = Net()                          # exact reference topology
    state = create_train_state(
        model, jax.random.PRNGKey(0),
        np.zeros((1, 32, 32, 3), np.float32), SGD(MOMENTUM),
    )
    step = make_train_step(model, SGD(MOMENTUM), mesh, constant_lr(LR))
    eval_step = make_eval_step(model, mesh)

    for epoch in range(EPOCHS):            # ddp.py:90
        train_pipe.set_epoch(epoch)        # ddp.py:92
        running, seen = 0.0, 0
        for i, batch in enumerate(train_pipe):
            state, metrics = step(state, batch)   # fwd+bwd+allreduce+sgd
            running += float(metrics["loss"])
            seen += 1
            if (i + 1) % LOG_EVERY == 0:   # reference print format
                print0(f"[{epoch + 1}, {i + 1:5d}] loss: {running / seen:.3f}")
                running, seen = 0.0, 0

    print0("Finished Training")
    save_params("./cifar_net.msgpack", state.params)   # ≙ torch.save (:118)

    correct = total = 0
    for batch in test_pipe:
        m = eval_step(state, batch)        # global counts, reduction in-step
        correct += int(m["correct"])
        total += int(m["count"])
    # Reference prints a hardcoded "10000 test images" (cifar_example.py:111);
    # real CIFAR gives exactly that, synthetic fallbacks report their size.
    print0(
        f"Accuracy of the network on the {total} test images: "
        f"{100 * correct // max(total, 1)} %"
    )


if __name__ == "__main__":
    main()
