#!/usr/bin/env python
"""Render the fused-conv verdict from captured TPU measurements.

Reads the e2e sweep rows in `benchmarks/results.jsonl` (non-smoke,
accelerator-backend) and the kernel microbench JSON lines under
`benchmarks/r4_capture/fusedk_*.out`, and prints:

  1. a per-(batch, window) e2e table: unfused vs each fused variant,
  2. a per-stage-shape kernel table: XLA vs Pallas per block_b,
  3. the verdict line VERDICT r3 item 1 asks for — which variant (if any)
     beats unfused at the headline operating point, with the margin.

Pure file parsing (no device); run any time:
    python tools/fused_verdict.py
    python tools/fused_verdict.py --model resnet50
"""

from __future__ import annotations

import argparse
import glob
import json
from collections import defaultdict
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
RESULTS = ROOT / "benchmarks" / "results.jsonl"
CAPTURE = ROOT / "benchmarks" / "r4_capture"


def load_results(metric_substr: str):
    rows = []
    try:
        lines = RESULTS.read_text().splitlines()
    except OSError:
        return rows
    for line in lines:
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (r.get("value") and not r.get("smoke")
                and r.get("backend") not in (None, "cpu")
                and metric_substr in r.get("metric", "")):
            rows.append(r)
    return rows


def variant_key(cfg: dict) -> str:
    fs = cfg.get("fused_stages") or ""
    if not fs:
        return "unfused"
    return f"fused[{fs}]" + ("+bwd" if cfg.get("fused_bwd") else "")


def e2e_table(rows):
    # newest row wins per (batch, window, variant)
    cells: dict = {}
    for r in sorted(rows, key=lambda r: r.get("ts", "")):
        cfg = r.get("config") or {}
        if cfg.get("xent") == "pallas":
            continue  # fused sweeps run jnp xent; keep cells like-for-like
        key = (cfg.get("per_chip_batch"), cfg.get("steps_per_call"),
               variant_key(cfg))
        cells[key] = r
    variants = sorted({k[2] for k in cells}, key=lambda v: (v != "unfused", v))
    points = sorted({(k[0], k[1]) for k in cells},
                    key=lambda p: (p[0] or 0, p[1] or 0))
    if not points:
        return None, variants, cells
    head = "| batch/chip | window | " + " | ".join(variants) + " |"
    sep = "|---" * (len(variants) + 2) + "|"
    lines = [head, sep]
    for b, w in points:
        row = [f"| {b} | {w} "]
        base = cells.get((b, w, "unfused"))
        for v in variants:
            r = cells.get((b, w, v))
            if r is None:
                row.append("| — ")
                continue
            val = f"{r['value']:,.0f}"
            if r.get("mfu") is not None:
                val += f" (.{round(r['mfu'] * 1000):03d})"
            if base and v != "unfused":
                val += f" {100 * (r['value'] / base['value'] - 1):+.1f}%"
            row.append(f"| {val} ")
        lines.append("".join(row) + "|")
    return "\n".join(lines), variants, cells


def kernel_table():
    recs = []
    # captured/ holds the watcher-preserved (committed) copies; the top
    # level holds this session's live outputs — read both, dedup by path
    # basename preferring the live copy.
    paths = {Path(p).name: p
             for p in sorted(glob.glob(str(CAPTURE / "captured"
                                           / "fusedk_*.out")))}
    paths.update({Path(p).name: p
                  for p in sorted(glob.glob(str(CAPTURE / "fusedk_*.out")))})
    for path in sorted(paths.values()):
        for line in Path(path).read_text().splitlines():
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            if r.get("device") and r.get("device") != "cpu" and r.get("ms"):
                recs.append(r)
    if not recs:
        return None
    by_point = defaultdict(list)
    for r in recs:
        by_point[(tuple(r["shape"]), bool(r.get("grad")),
                  bool(r.get("residual")))].append(r)
    lines = ["| shape | mode | xla ms (%pk) | best pallas ms (%pk) | "
             "block_b | speedup |", "|---|---|---|---|---|---|"]
    for (shape, grad, res), rs in sorted(by_point.items()):
        xla = [r for r in rs if r["impl"] == "xla"]
        pal = [r for r in rs if r["impl"].startswith("pallas")]
        if not xla or not pal:
            continue
        x = min(xla, key=lambda r: r["ms"])
        p = min(pal, key=lambda r: r["ms"])
        mode = ("fwd+bwd" if grad else "fwd") + ("+res" if res else "")
        lines.append(
            f"| {'x'.join(map(str, shape))} | {mode} "
            f"| {x['ms']} ({x.get('pct_peak')}) "
            f"| {p['ms']} ({p.get('pct_peak')}) [{p['impl']}] "
            f"| {p['block_b']} | {x['ms'] / p['ms']:.2f}x |")
    return "\n".join(lines) if len(lines) > 2 else None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18")
    ap.add_argument("--headline-batch", type=int, default=2048)
    ap.add_argument("--headline-window", type=int, default=30)
    args = ap.parse_args()

    rows = load_results(args.model)
    table, variants, cells = e2e_table(rows)
    print(f"# Fused-conv verdict ({args.model})\n")
    if table is None:
        print("No accelerator e2e rows yet — run `python bench.py "
              "--sweep-fused` on the chip (or wait for the r4 watcher).")
    else:
        print("## End-to-end (images/sec/chip, (MFU), % vs unfused)\n")
        print(table)

    kt = kernel_table()
    if kt:
        print("\n## Kernel microbench (best per shape)\n")
        print(kt)
    else:
        print("\n(no TPU kernel microbench captures under "
              "benchmarks/r4_capture/ yet)")

    # The verdict line.
    hb, hw = args.headline_batch, args.headline_window
    base = cells.get((hb, hw, "unfused")) if cells else None
    fused = [(v, cells[(hb, hw, v)]) for v in variants
             if v != "unfused" and (hb, hw, v) in cells] if cells else []
    print()
    if base and fused:
        best_v, best = max(fused, key=lambda kv: kv[1]["value"])
        margin = 100 * (best["value"] / base["value"] - 1)
        if margin > 0:
            print(f"VERDICT: {best_v} BEATS unfused at the headline point "
                  f"(b{hb}/w{hw}): {best['value']:,.0f} vs "
                  f"{base['value']:,.0f} img/s/chip ({margin:+.1f}%) — make "
                  f"it the headline config.")
        else:
            print(f"VERDICT: no fused variant beats unfused at the headline "
                  f"point (b{hb}/w{hw}); best is {best_v} at {margin:+.1f}% "
                  f"({best['value']:,.0f} vs {base['value']:,.0f}) — keep "
                  f"fused_stages default off, document as the Pallas "
                  f"exemplar.")
    else:
        print("VERDICT: pending — headline-point measurements for both "
              "unfused and fused variants not yet captured.")


if __name__ == "__main__":
    main()
