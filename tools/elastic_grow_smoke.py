#!/usr/bin/env python
"""Elastic GROW smoke: 3 CPU processes, rank 2 SIGTERMed, relaunched,
rejoined — world 3 → 2 → 3 (the `tools/run_tier1.sh --elastic-grow` lane).

The full production round trip, with a REAL external SIGTERM and a REAL
relaunch (a fresh OS process, not the in-process `relaunch:` twin):

1. three workers train; a one-shot ``delay:`` fault pins rank 2 at its
   step-2 boundary so the external SIGTERM lands mid-training
   deterministically;
2. rank 2 departs gracefully (exit 143), survivors shrink to world 2;
3. the relaunched rank 2 — spawned by this driver the way a supervisor
   would — discovers the live run through the membership ledger
   (``resilience.elastic_join=always``), publishes a fenced join request,
   and the members regrow the mesh to world 3;
4. every process finishes both epochs; verdicts below.

Verdicts (exit 0 clean, 1 on any violation):

- exit codes: old rank 2 exits 143, everyone else (rejoined rank 2
  included) exits 0 — zero operator action beyond the relaunch;
- the membership ledger records world 3 → 2 (graceful, rank 2 departed)
  → 3 (grow, rank 2 joined, token echoed);
- all three final param digests are identical, and the final params
  match a single-device oracle replaying the exact 3→2→3 sample
  schedule reconstructed from the ledger alone (atol 2e-5);
- ``obsctl timeline`` over NOTHING but the run dir reconstructs
  departure → shrink-regroup → join → grow-regroup → completion.

Archives ``artifacts/elastic_grow_report.json`` and the timeline.
"""

from __future__ import annotations

import json
import pickle
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # the driver imports tpu_dp for the oracle
    sys.path.insert(0, str(REPO))

_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
out_path = sys.argv[4]; join = len(sys.argv) > 5 and sys.argv[5] == "join"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpu_dp.config import Config
from tpu_dp.train.trainer import run_elastic
from tpu_dp.resilience import PreemptedError

cfg = Config()
cfg.data.dataset = "synthetic"
cfg.data.synthetic_train_size = 96
cfg.data.synthetic_test_size = 16
cfg.data.batch_size = 4
cfg.train.epochs = 2
cfg.train.log_every = 100
cfg.train.eval_at_end = False
cfg.train.steps_per_call = 1
cfg.train.ckpt_dir = ckpt
cfg.train.ckpt_async = False
cfg.train.obs = "basic"
cfg.resilience.elastic = True
cfg.parallel.coordinator_address = f"127.0.0.1:{port}"
cfg.parallel.num_processes = 3
cfg.parallel.process_id = rank
if join:
    # The supervisor's relaunch command: join the live run, never
    # bootstrap (and never trust this incarnation's local view).
    cfg.resilience.elastic_join = "always"
else:
    cfg.resilience.elastic_join = "never"
    # One-shot delay pins rank 2 at its step-2 boundary for 3s — the
    # deterministic window for the driver's REAL external SIGTERM.
    cfg.resilience.fault = "delay:step=2,rank=2,ms=3000"

try:
    tr, result = run_elastic(cfg)
except PreemptedError as e:
    print("GROW_LEFT", rank, repr(str(e)), flush=True)
    sys.exit(143)
from tpu_dp.obs.counters import counters
digest = float(sum(
    np.abs(np.asarray(l)).sum()
    for l in jax.tree_util.tree_leaves(tr.state.params)))
host_params = jax.tree_util.tree_map(np.asarray, tr.state.params)
with open(out_path, "wb") as f:
    pickle.dump(dict(rank=rank, world=tr.ctx.process_count,
                     new_rank=tr.ctx.process_index, digest=digest,
                     params=host_params,
                     record=tr.elastic.record.to_json(),
                     counters=counters.snapshot()), f)
print("GROW_OK", rank, flush=True)
sys.exit(0)
"""


def _oracle_params(records: list[dict], num_examples: int, batch: int = 4,
                   epochs: int = 2, seed: int = 0):
    """Single-device replay of the ledger's 3→2→3 sample schedule."""
    import jax

    from tpu_dp.config import Config
    from tpu_dp.data.cifar import load_dataset
    from tpu_dp.data.sampler import ShardedSampler, elastic_resplit
    from tpu_dp.models import Net
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, create_train_state, make_train_step
    from tpu_dp.train.schedule import make_schedule

    defaults = Config()
    ds = load_dataset("synthetic", "./data", train=True,
                      allow_synthetic=True,
                      synthetic_num_examples=num_examples, seed=seed)

    def streams(epoch, prior, world):
        if not prior:
            out = []
            for r in range(world):
                s = ShardedSampler(len(ds), world, r, shuffle=True,
                                   seed=seed)
                s.set_epoch(epoch)
                out.append(s.shard_indices())
            return out
        return [elastic_resplit(len(ds), True, seed, epoch, batch, prior,
                                world, r) for r in range(world)]

    def segments_for_epoch(e):
        touching = [r for r in records[1:]
                    if (r.get("resume") or {}).get("epoch") == e]
        if touching:
            last = touching[-1]
            lineage = [list(map(int, seg))
                       for seg in last["resume"]["lineage"]]
            segs = [(lineage[:i], int(w), int(s))
                    for i, (w, s) in enumerate(lineage)]
            segs.append((lineage, int(last["world"]), None))
            return segs
        world = int(records[0]["world"])
        for r in records[1:]:
            if (r.get("resume") or {}).get("epoch", 10 ** 9) < e:
                world = int(r["world"])
        return [([], world, None)]

    mesh1 = dist.data_mesh(num_devices=1)
    model, opt = Net(), SGD(defaults.optim.momentum)
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    step = make_train_step(model, opt, mesh1, make_schedule(
        "constant", defaults.optim.lr, 1, 0, 0.0))
    for epoch in range(epochs):
        for prior, world, steps in segments_for_epoch(epoch):
            segs = streams(epoch, prior, world)
            n = (min(len(s) for s in segs) // batch
                 if steps is None else steps)
            for k in range(n):
                sel = np.concatenate(
                    [s[k * batch:(k + 1) * batch] for s in segs])
                state, _ = step(state, {"image": ds.images[sel],
                                        "label": ds.labels[sel]})
    return state


def main() -> int:
    import os

    art = REPO / "artifacts"
    art.mkdir(parents=True, exist_ok=True)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    keep = os.environ.get("TPU_DP_SMOKE_DIR")
    tmp = (Path(keep) if keep
           else Path(tempfile.mkdtemp(prefix="tpu_dp_grow_smoke.")))
    tmp.mkdir(parents=True, exist_ok=True)
    script = tmp / "worker.py"
    script.write_text(_WORKER)
    ckpt = tmp / "ck"
    outs = [tmp / f"out{r}.pkl" for r in range(3)]
    rejoin_out = tmp / "out2_rejoin.pkl"

    env = dict(os.environ, PYTHONPATH=str(REPO))
    env.pop("TPU_DP_FAULT", None)
    t0 = time.time()

    def spawn(rank, out_path, join=False):
        argv = [sys.executable, str(script), str(rank), port, str(ckpt),
                str(out_path)] + (["join"] if join else [])
        return subprocess.Popen(argv, cwd=REPO, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    procs = [spawn(r, outs[r]) for r in range(3)]
    failures: list[str] = []
    logs: dict[str, str] = {}

    # The external SIGTERM: wait for training to be underway (rank 2's
    # heartbeat file), then deliver — the delay: fault pins the window.
    hb = ckpt / "obs" / "heartbeat_r00002.jsonl"
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if hb.exists() and hb.read_text().count("\n") >= 1:
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.05)
    procs[2].send_signal(signal.SIGTERM)

    # The relaunch, immediately — the way an eager supervisor would. The
    # joiner's admission handshake tolerates the shrink still being in
    # flight (it waits for the membership record that excludes sid 2,
    # then requests admission to the next epoch).
    rejoin = spawn(2, rejoin_out, join=True)

    try:
        for name, p in [("r0", procs[0]), ("r1", procs[1]),
                        ("r2-old", procs[2]), ("r2-rejoin", rejoin)]:
            logs[name] = p.communicate(timeout=300)[0].decode()
    except subprocess.TimeoutExpired:
        for p in procs + [rejoin]:
            if p.poll() is None:
                p.kill()
        print("FAIL: grow smoke timed out", file=sys.stderr)
        for name, log in logs.items():
            print(f"--- {name}\n{log[-2000:]}", file=sys.stderr)
        return 1

    want = {"r0": (procs[0], 0), "r1": (procs[1], 0),
            "r2-old": (procs[2], 143), "r2-rejoin": (rejoin, 0)}
    for name, (p, rc) in want.items():
        if p.returncode != rc:
            failures.append(f"{name}: exit {p.returncode} != {rc}")

    results = {}
    for r, path in ((0, outs[0]), (1, outs[1]), (2, rejoin_out)):
        if path.exists():
            results[r] = pickle.loads(path.read_bytes())
        else:
            failures.append(f"rank {r}: no result dump")

    records: list[dict] = []
    worlds: list[int] = []
    mem_root = ckpt / "membership"
    gen_dirs = sorted(mem_root.iterdir()) if mem_root.exists() else []
    if len(gen_dirs) == 1:
        records = [json.loads(p.read_text())
                   for p in sorted(gen_dirs[0].glob("epoch_*.json"))]
        worlds = [r["world"] for r in records]
        if worlds != [3, 2, 3]:
            failures.append(f"world history {worlds} != [3, 2, 3]")
        else:
            if [d["sid"] for d in records[1]["departed"]] != [2]:
                failures.append(f"shrink departed: {records[1]['departed']}")
            if (records[2]["reason"] != "grow"
                    or [j["sid"] for j in records[2]["joined"]] != [2]):
                failures.append(f"grow record wrong: {records[2]}")
    else:
        failures.append(f"expected one ledger generation, got {gen_dirs}")

    if len(results) == 3:
        digests = {r: results[r]["digest"] for r in results}
        if len(set(digests.values())) != 1:
            failures.append(f"final params diverged across ranks: {digests}")
        if any(results[r]["world"] != 3 for r in results):
            failures.append(
                f"not everyone ended at world 3: "
                f"{ {r: results[r]['world'] for r in results} }")
        if records and not failures:
            import jax

            oracle = _oracle_params(records, num_examples=96)
            for x, y in zip(
                jax.tree_util.tree_leaves(results[0]["params"]),
                jax.tree_util.tree_leaves(oracle.params),
            ):
                if not np.allclose(np.asarray(x), np.asarray(y),
                                   atol=2e-5):
                    failures.append("final params do not match the "
                                    "single-device 3→2→3 oracle")
                    break

    # obsctl timeline from the artifacts alone: the grow story in order.
    timeline_kinds: list[str] = []
    try:
        from tpu_dp.obs import obsctl

        out = obsctl.build_timeline(obsctl.RunArtifacts(ckpt))
        timeline_kinds = [e["kind"] for e in out["events"]]
        story = ["elastic_departure", "elastic_regroup", "rank_joined",
                 "elastic_grow"]
        positions = [timeline_kinds.index(k) for k in story]
        positions.append(len(timeline_kinds) - 1
                         - timeline_kinds[::-1].index("epoch_complete"))
        if positions != sorted(positions):
            failures.append(f"timeline story out of order: "
                            f"{list(zip(story, positions))}")
        (art / "elastic_grow_timeline.json").write_text(json.dumps(out))
    except Exception as e:  # noqa: BLE001 — verdict, not crash
        failures.append(f"obsctl timeline failed: {e}")

    report = {
        "ok": not failures,
        "failures": failures,
        "wall_s": round(time.time() - t0, 1),
        "exit_codes": {n: p.returncode for n, (p, _) in want.items()},
        "world_history": worlds,
        "membership_records": records,
        "timeline_events": len(timeline_kinds),
        "counters": {r: {k: v for k, v in results[r]["counters"].items()
                         if k.startswith("elastic")}
                     for r in results},
    }
    (art / "elastic_grow_report.json").write_text(
        json.dumps(report, indent=2, default=str))
    print(f"elastic grow smoke: {'OK' if not failures else 'FAIL'} "
          f"({report['wall_s']}s) — artifacts/elastic_grow_report.json")
    if failures:
        for f in failures:
            print(f"  FAIL: {f}", file=sys.stderr)
        for name, log in logs.items():
            print(f"--- {name}\n{log[-2500:]}", file=sys.stderr)
        return 1
    if not keep:
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
