#!/usr/bin/env python
"""Measure how well the input feed overlaps device execution.

Answers, with a number, where the end-to-end vs device-bound throughput
gap comes from (`benchmarks/longrun_r3/README.md`: ~2,200 img/s end-to-end
vs ~34,000 img/s for the same step in `bench.py`): runs the production
window loop (`DataPipeline.windows` -> `make_multi_step`, the exact
`Trainer.train_epoch` dispatch pattern) over synthetic data and splits
each epoch's wall time into

  wait_s     consumer time blocked waiting for the next staged window
             (host staging + host->device transfer NOT hidden by prefetch),
  step_s     time in dispatch + the device fence (device execution).

If wait_s ~= 0 the feed fully overlaps and the end-to-end gap is
device/transport-side; if wait_s dominates, the host path (numpy gather +
stack + relay transfer on this single-core host) is the bottleneck and
deeper prefetch cannot help past CPU saturation. Run with --prefetch 0 for
the no-overlap baseline.

--feed resident (or both) additionally measures the device-resident path
(`DataPipeline.index_windows` -> `make_multi_step_resident`, the
production default): the dataset is staged in HBM once and each window
ships only int32 indices, so wait_s should collapse to ~0 regardless of
host speed — the designed fix for the end-to-end gap (VERDICT r4
next-steps #3).

Prints one JSON line per (feed, prefetch, epoch).

  python tools/bench_feed_overlap.py                    # longrun shape, TPU
  python tools/bench_feed_overlap.py --platform cpu --train-size 2048 \
      --per-chip-batch 256 --window 4                   # harness smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-chip-batch", type=int, default=2048)
    ap.add_argument("--window", type=int, default=24,
                    help="steps per dispatch (longrun_r3: 24 = one epoch)")
    ap.add_argument("--train-size", type=int, default=50000)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--prefetch", default="0,2,4",
                    help="comma-separated prefetch depths to compare")
    ap.add_argument("--platform", default=None, choices=["cpu"],
                    help="force cpu (harness smoke test; the env's "
                         "sitecustomize pins the tpu backend)")
    ap.add_argument("--feed", default="both",
                    choices=["streaming", "resident", "both"],
                    help="which feed path(s) to measure")
    args = ap.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.data.pipeline import DataPipeline
    from tpu_dp.models import build_model
    from tpu_dp.parallel import dist
    from tpu_dp.train import SGD, cosine_lr, create_train_state, make_multi_step

    mesh = dist.data_mesh()
    gb = args.per_chip_batch * int(mesh.devices.size)
    ds = make_synthetic(args.train_size, 10, seed=0, name="overlap-bench")
    model = build_model("resnet18", num_classes=10, dtype=jnp.bfloat16)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state0 = create_train_state(model, jax.random.PRNGKey(0),
                                np.zeros((1, 32, 32, 3), np.float32), opt)
    steps = (args.train_size // gb // args.window) * args.window
    # One schedule and one pipeline recipe shared by both feeds: the tool's
    # whole point is an apples-to-apples comparison.
    sched = cosine_lr(0.4, max(steps, 1) * args.epochs, 1)

    def make_pipe(pf):
        return DataPipeline(ds, gb, mesh, shuffle=True, seed=0,
                            drop_remainder=True, prefetch=pf)

    loop = make_multi_step(model, opt, mesh, sched, num_steps=args.window)

    def run(feed, pf, pipe, step_fn):
        # The scanned loop donates its input state; each run needs a
        # fresh copy or run 2 would step on run 1's deleted buffers.
        state = jax.tree_util.tree_map(jnp.copy, state0)
        for epoch in range(args.epochs):
            pipe.set_epoch(epoch)
            wait_s = step_s = 0.0
            n_imgs = 0
            t_epoch = time.perf_counter()
            it = (pipe.index_windows(args.window) if feed == "resident"
                  else pipe.windows(args.window))
            while True:
                t0 = time.perf_counter()
                try:
                    n, item = next(it)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                if n == 1:
                    continue  # trailing singles: not the measured path
                state, m = step_fn(state, item)
                # Fence: scalar fetch (block_until_ready can return early
                # on this relay transport — docs/DESIGN.md).
                float(m["loss"][-1])
                t2 = time.perf_counter()
                wait_s += t1 - t0
                step_s += t2 - t1
                n_imgs += n * gb
            total = time.perf_counter() - t_epoch
            rec = {"feed": feed, "prefetch": pf, "epoch": epoch,
                   "img_per_s": round(n_imgs / total, 1),
                   "total_s": round(total, 3),
                   "wait_s": round(wait_s, 3),
                   "step_s": round(step_s, 3),
                   "wait_frac": round(wait_s / total, 3),
                   "window": args.window, "global_batch": gb,
                   "backend": jax.default_backend(),
                   "device": jax.devices()[0].device_kind}
            print(json.dumps(rec), flush=True)
            # epoch 0 of each run includes compile (cached after the
            # first) — compare epochs >= 1.

    if args.feed in ("streaming", "both"):
        for pf in [int(p) for p in args.prefetch.split(",")]:
            run("streaming", pf, make_pipe(pf), loop)

    if args.feed in ("resident", "both"):
        from tpu_dp.train.step import make_multi_step_resident

        pipe = make_pipe(0)
        rdata = pipe.resident_data()
        rloop = make_multi_step_resident(model, opt, mesh, sched,
                                         num_steps=args.window)
        run("resident", 0, pipe,
            lambda state, idx: rloop(state, rdata, idx))


if __name__ == "__main__":
    main()
