#!/usr/bin/env python
"""Elastic kill-one-rank smoke: 3 CPU processes, rank 2 preempted, the
survivors finish on world 2 — the `tools/run_tier1.sh --elastic` lane.

Spawns three `train.py`-equivalent workers (Trainer driven directly, gloo
CPU collectives), delivers a deterministic SIGTERM to rank 2 at step 2 via
``TPU_DP_FAULT=preempt:``, and verdicts the run:

- rank 2 exits 143 (terminated-by-request), ranks 0/1 exit 0 — no
  operator action;
- the membership ledger records epoch 1 with rank 2 departed;
- the survivors' final params are bit-identical to each other;
- the regroup is attributed in the obs counters.

Archives the membership ledger directory and a regroup report under
``artifacts/elastic/`` (the CI artifacts reviewers diff). Exit 0 on a
clean regroup, 1 on any violated check.
"""

from __future__ import annotations

import json
import pickle
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_WORKER = r"""
import os, pickle, sys
rank = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
out_path = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpu_dp.config import Config
from tpu_dp.train.trainer import Trainer
from tpu_dp.resilience import PreemptedError

cfg = Config()
cfg.data.dataset = "synthetic"
cfg.data.synthetic_train_size = 48
cfg.data.synthetic_test_size = 16
cfg.data.batch_size = 4
cfg.train.epochs = 2
cfg.train.log_every = 100
cfg.train.eval_at_end = False
cfg.train.steps_per_call = 1
cfg.train.ckpt_dir = ckpt
cfg.train.ckpt_async = False
cfg.train.obs = "basic"
cfg.resilience.elastic = True
cfg.resilience.fault = "preempt:step=2,rank=2"
cfg.parallel.coordinator_address = f"127.0.0.1:{port}"
cfg.parallel.num_processes = 3
cfg.parallel.process_id = rank

tr = Trainer(cfg)
try:
    tr.fit()
except PreemptedError:
    sys.exit(143)
from tpu_dp.obs.counters import counters
digest = float(sum(
    np.abs(np.asarray(l)).sum()
    for l in jax.tree_util.tree_leaves(tr.state.params)))
with open(out_path, "wb") as f:
    pickle.dump(dict(rank=rank, world=tr.ctx.process_count,
                     new_rank=tr.ctx.process_index, digest=digest,
                     record=tr.elastic.record.to_json(),
                     counters=counters.snapshot()), f)
sys.exit(0)
"""


def main() -> int:
    art = REPO / "artifacts" / "elastic"
    art.mkdir(parents=True, exist_ok=True)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    import os

    # TPU_DP_SMOKE_DIR pins the run dir so a downstream consumer (the
    # --obsctl tier-1 lane runs `obsctl timeline` over this very run's
    # artifacts) can find it; default stays a throwaway tempdir.
    keep = os.environ.get("TPU_DP_SMOKE_DIR")
    tmp = (Path(keep) if keep
           else Path(tempfile.mkdtemp(prefix="tpu_dp_elastic_smoke.")))
    tmp.mkdir(parents=True, exist_ok=True)
    script = tmp / "worker.py"
    script.write_text(_WORKER)
    ckpt = tmp / "ck"
    outs = [tmp / f"out{r}.pkl" for r in range(3)]

    env = dict(os.environ, PYTHONPATH=str(REPO))
    env.pop("TPU_DP_FAULT", None)
    t0 = time.time()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), port, str(ckpt), str(outs[r])],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(3)
    ]
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=300)[0].decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        print("FAIL: elastic smoke timed out", file=sys.stderr)
        for i, log in enumerate(logs):
            print(f"--- rank {i}\n{log[-2000:]}", file=sys.stderr)
        return 1

    failures: list[str] = []
    want = {0: 0, 1: 0, 2: 143}
    for r, p in enumerate(procs):
        if p.returncode != want[r]:
            failures.append(f"rank {r}: exit {p.returncode} != {want[r]}")
    results = {}
    for r in (0, 1):
        if outs[r].exists():
            results[r] = pickle.loads(outs[r].read_bytes())
        else:
            failures.append(f"rank {r}: no result dump")
    record = None
    if len(results) == 2:
        a, b = results[0], results[1]
        record = a["record"]
        if a["world"] != 2 or b["world"] != 2:
            failures.append(f"survivor world {a['world']}/{b['world']} != 2")
        if record["epoch"] != 1 or record["members"] != [0, 1]:
            failures.append(f"membership record wrong: {record}")
        if [d["sid"] for d in record["departed"]] != [2]:
            failures.append(f"departed wrong: {record['departed']}")
        if a["digest"] != b["digest"]:
            failures.append(
                f"survivor params diverged: {a['digest']} != {b['digest']}")
        for r in (0, 1):
            c = results[r]["counters"]
            if c.get("elastic.regroups") != 1 or c.get("elastic.lost_ranks") != 1:
                failures.append(f"rank {r}: regroup counters wrong: "
                                f"{ {k: v for k, v in c.items() if k.startswith('elastic')} }")

    # Archive: the membership ledger + the verdict report.
    mem_root = ckpt / "membership"
    gen_dirs = sorted(mem_root.iterdir()) if mem_root.exists() else []
    ledger_art = art / "membership"
    if ledger_art.exists():
        shutil.rmtree(ledger_art)
    if gen_dirs:
        shutil.copytree(gen_dirs[-1], ledger_art)
    report = {
        "ok": not failures,
        "failures": failures,
        "wall_s": round(time.time() - t0, 1),
        "exit_codes": [p.returncode for p in procs],
        "membership_record": record,
        "counters": {r: {k: v for k, v in results[r]["counters"].items()
                         if k.startswith("elastic")}
                     for r in results},
    }
    (art / "regroup_report.json").write_text(json.dumps(report, indent=2))
    print(f"elastic smoke: {'OK' if not failures else 'FAIL'} "
          f"({report['wall_s']}s) — artifacts/elastic/regroup_report.json")
    if failures:
        for f in failures:
            print(f"  FAIL: {f}", file=sys.stderr)
        for i, log in enumerate(logs):
            print(f"--- rank {i}\n{log[-2000:]}", file=sys.stderr)
        return 1
    if not keep:  # a pinned dir belongs to the caller (the obsctl lane)
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
