#!/usr/bin/env bash
# Tier-1 verify gate (ROADMAP.md), wrapped so CI and humans run the exact
# same command. Usage:
#
#   tools/run_tier1.sh               # full tier-1 suite (CPU backend)
#   tools/run_tier1.sh --resilience  # fast lane: only -m resilience tests
#   tools/run_tier1.sh --shard-update # parity lane: the sharded weight-
#                                    # update suite (-m shard_update) — the
#                                    # sharded-vs-replicated bitwise
#                                    # property, checkpoint resharding, and
#                                    # the sharded kill+resume contract
#   tools/run_tier1.sh --dplint      # static-analysis lane: all three
#                                    # dplint levels (AST + jaxpr + compiled
#                                    # HLO) over tpu_dp/ + the -m analysis
#                                    # tests; fails on any unsuppressed
#                                    # finding. Emits artifacts/
#                                    # dplint_report.json and artifacts/
#                                    # collective_fingerprint.json.
#   tools/run_tier1.sh --lint        # host-protocol + concurrency lane:
#                                    # dplint Level 4 (DP401-DP405) AND
#                                    # Level 5 (DP501-DP505) over the
#                                    # tree (both must be clean; archives
#                                    # artifacts/hostproto_report.json +
#                                    # artifacts/concurrency_report.json),
#                                    # planted tampered fixtures that
#                                    # MUST fail per level, then the
#                                    # -m "lint or conc" tests.
#   tools/run_tier1.sh --obs         # telemetry lane: a 10-step obs=full
#                                    # smoke run (archives its metrics.jsonl
#                                    # and Perfetto trace under artifacts/)
#                                    # + the -m obs tests.
#   tools/run_tier1.sh --elastic     # elastic world-size lane: the
#                                    # kill-one-rank smoke (3 CPU
#                                    # processes, rank 2 preempted at
#                                    # step 2, survivors finish on
#                                    # world 2; archives the membership
#                                    # ledger + regroup report under
#                                    # artifacts/elastic/) + the
#                                    # -m elastic tests (protocol units
#                                    # AND the 3-process subprocess
#                                    # suite).
#   tools/run_tier1.sh --elastic-grow # elastic grow lane: the full
#                                    # preempt→shrink→relaunch→regrow
#                                    # round trip — 3 CPU processes, a
#                                    # REAL external SIGTERM to rank 2
#                                    # mid-training, a REAL relaunch that
#                                    # rejoins through the membership
#                                    # ledger; asserts world 3→2→3, final
#                                    # params vs the single-device oracle
#                                    # (atol 2e-5), and that `obsctl
#                                    # timeline` reconstructs departure →
#                                    # regroup → join → grow-regroup →
#                                    # completion from artifacts alone.
#                                    # Archives artifacts/
#                                    # elastic_grow_report.json (+ the
#                                    # timeline), then the -m elastic
#                                    # tests.
#   tools/run_tier1.sh --guard       # guardrails lane: two exit-coded
#                                    # smokes — NaN-skip (injected
#                                    # nan:step=3, action=skip: the run
#                                    # must complete with exactly one
#                                    # quarantine record) and
#                                    # spike-rollback (injected 1e6x
#                                    # spike, action=rollback: the run
#                                    # must rewind to a snapshot,
#                                    # tombstone, replay, and complete) —
#                                    # archiving artifacts/
#                                    # quarantine.jsonl + artifacts/
#                                    # guard_report.json, then the
#                                    # -m guard tests.
#   tools/run_tier1.sh --obsctl      # forensic-tooling lane: runs the
#                                    # guard spike-rollback smoke (at
#                                    # obs=full, so flight-recorder dumps,
#                                    # schema-3 efficiency records and
#                                    # rollback generations all land) and
#                                    # the elastic kill-one-rank smoke,
#                                    # then drives `obsctl` over nothing
#                                    # but their artifact directories:
#                                    # timeline (exit-coded, archived),
#                                    # merge-trace (validated Perfetto),
#                                    # and diff (clean run vs its own
#                                    # baseline must exit 0; a tampered
#                                    # baseline must exit 1 — the CI gate
#                                    # proof). Archives artifacts/
#                                    # obsctl_report.json + the timeline
#                                    # and merged trace, then the -m obs
#                                    # tests (which now cover flightrec /
#                                    # costs / promfile / obsctl).
#   tools/run_tier1.sh --commprof   # comm-attribution lane: a profiled
#                                    # 10-step sharded-update smoke on the
#                                    # 8-device CPU mesh with an in-run
#                                    # capture window ([4,6)); exit-coded
#                                    # checks that the parsed breakdown's
#                                    # collective counts reconcile exactly
#                                    # with the program's fingerprint
#                                    # schedule and the wire bytes with
#                                    # quant.wire_report; archives
#                                    # artifacts/comm_report.json; then
#                                    # `obsctl watch --replay` must exit 0
#                                    # on the clean run and 1 on a
#                                    # tampered stream (the live-alert
#                                    # gate proof), then the -m commprof
#                                    # tests.
#   tools/run_tier1.sh --overlap    # bucketed-overlap lane (docs/PERF.md
#                                    # "Overlapped collectives"): a
#                                    # profiled 10-step sharded smoke with
#                                    # train.bucket_mb armed (int8 wire,
#                                    # K=2 buckets on Net) — exit-coded
#                                    # checks that the commprof window
#                                    # reconciles exactly K bucketed
#                                    # exchanges per step (per the
#                                    # fingerprint schedule), that the
#                                    # per-bucket wire bytes are
#                                    # byte-exact vs quant.wire_report,
#                                    # and that obs.overlap_frac /
#                                    # obs.goodput published; a TAMPERED
#                                    # single-bucket baseline (fabricated
#                                    # near-zero exposed comm) must make
#                                    # `obsctl diff` exit 1. Archives
#                                    # artifacts/overlap_report.json,
#                                    # then the -m overlap tests.
#   tools/run_tier1.sh --quant      # quantized-collectives lane: an int8
#                                    # BENCH point on the 8-device CPU
#                                    # mesh with exit-coded quant-block
#                                    # checks (wire compression > 3x vs
#                                    # f32, zero overflow blocks; archives
#                                    # artifacts/quant_report.json), then
#                                    # the -m quant suite (codec units,
#                                    # f32/bf16/int8 parity harness,
#                                    # error-feedback ablation, guard/NaN
#                                    # interaction, residual checkpoint
#                                    # resharding + kill/resume).
#   tools/run_tier1.sh --tune       # self-tuning lane (docs/TUNE.md): a
#                                    # real seeded 3-config search on the
#                                    # 8-virtual-device CPU mesh (tiny
#                                    # budget, fenced trials, chaos gate)
#                                    # with --plant-fragile ON — the gate
#                                    # must reject the fabricated
#                                    # leaderboard top with receipts; the
#                                    # written tuned.json is re-earned by
#                                    # `tune validate` (exit 0), a
#                                    # byte-identical profile must fall
#                                    # out of a cached re-search, a
#                                    # tampered claims block must fail
#                                    # validation (exit 1), and bench.py
#                                    # must refuse a mis-keyed profile
#                                    # (exit 2). Archives artifacts/
#                                    # tune_report.json + tuned.json,
#                                    # then the -m tune suite.
#   tools/run_tier1.sh --chaos      # composed-fault chaos lane
#                                    # (docs/CHAOS.md): 5 seeded trials
#                                    # over the default fault palette —
#                                    # the generator samples multi-fault
#                                    # schedules, runs the real train.py
#                                    # under a supervisor loop, and the
#                                    # invariant auditor verdicts each
#                                    # trial (no wedge, legal exits,
#                                    # artifacts parse, coverage, params
#                                    # bitwise vs the never-faulted
#                                    # oracle); archives artifacts/
#                                    # chaos_report.json + the minimized
#                                    # spec of any failure. The
#                                    # --tamper-oracle self-test must
#                                    # exit nonzero (the gate can trip),
#                                    # then the -m chaos suite runs —
#                                    # units AND the composed-fault
#                                    # acceptance trio (bitrot-before-
#                                    # rollback, SDC-during-grow,
#                                    # preempt-mid-rollback-regroup).
#   tools/run_tier1.sh --fleet       # fleet-telemetry lane: the straggler
#                                    # smoke — 3 real CPU training
#                                    # processes with rank 2 delay-poisoned
#                                    # at steps 14/16/18, then
#                                    # `obsctl fleet --replay` over the
#                                    # artifacts alone must exit 1 with
#                                    # BOTH rule grammars tripping (the
#                                    # threshold rule fleet.skew_ratio>3
#                                    # and the self-baselining
#                                    # anomaly:step_time_ms 12) and every
#                                    # >=3x skew record naming rank 2 at
#                                    # an injected step; the clean twin
#                                    # under the same rules must exit 0,
#                                    # and the published fleet.jsonl must
#                                    # re-read under the schema check.
#                                    # Archives artifacts/
#                                    # fleet_report.json, then the
#                                    # -m fleet tests (shared tail,
#                                    # stream tailer, skew/anomaly math,
#                                    # elastic alignment, obsctl fleet).
#   tools/run_tier1.sh --serve       # serving lane: a 200-request mixed-
#                                    # size synthetic load through the full
#                                    # queue → batcher → compiled-forward
#                                    # pipeline on the 8-device CPU mesh
#                                    # (exit 1 on any counter/ground-truth
#                                    # mismatch or post-warmup retrace;
#                                    # archives artifacts/serve_report.json
#                                    # with SLO attainment + shed counts)
#                                    # + the -m serve tests.
#   tools/run_tier1.sh --serve-elastic # self-healing serving lane: the
#                                    # chaos scenario matrix — 2 replicas
#                                    # over the 8-device CPU mesh, bursty
#                                    # two-class traffic, replica 0 delay-
#                                    # poisoned, replica 1 killed mid-load
#                                    # (leave: fault, the SIGTERM twin)
#                                    # then rejoined, one hot weight swap.
#                                    # Exit-coded audit: exact books incl.
#                                    # per-class, typed shed reasons only,
#                                    # class-0 attainment >= floor, both
#                                    # model versions served; obsctl must
#                                    # rebuild drain → swap → rejoin from
#                                    # the run dir alone and the serve
#                                    # diff gate must pass clean AND trip
#                                    # on a tampered baseline. Archives
#                                    # artifacts/serve_elastic_report.json
#                                    # + serve_elastic_timeline.json, then
#                                    # the -m serve tests.
#
# Exit code is pytest's; the DOTS_PASSED line echoes the pass count the
# roadmap tracks across PRs.
set -o pipefail
cd "$(dirname "$0")/.."

LOG=${TIER1_LOG:-/tmp/_t1.log}

if [ "${1:-}" = "--resilience" ]; then
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m resilience \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--shard-update" ]; then
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m shard_update \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--dplint" ]; then
    # Level 3 included: the JSON findings report and the collective-schedule
    # fingerprint are CI artifacts (the fingerprint diff across commits is
    # the review record of any compiled-schedule change).
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python -m tpu_dp.analysis tpu_dp/ --json \
        --fingerprint-out artifacts/collective_fingerprint.json \
        > artifacts/dplint_report.json
    rc=$?
    if [ "$rc" -ne 0 ]; then
        cat artifacts/dplint_report.json
        exit "$rc"
    fi
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m analysis \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--lint" ]; then
    # Host-protocol + concurrency lane (Levels 4 and 5), both directions
    # for each level:
    # 1. the shipped tree must lint clean under `host` (DP401-DP405) AND
    #    `conc` (DP501-DP505) — exit 0, both reports archived;
    # 2. tampered fixture copies planted into a scratch package MUST
    #    exit 1 per level — proving each gate still bites, not just that
    #    the tree is quiet;
    # 3. the -m "lint or conc" pytest suites (fixtures fire exactly,
    #    engine boundaries, registry invariants, pragma twins).
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python -m tpu_dp.analysis host --json \
        > artifacts/hostproto_report.json
    rc=$?
    if [ "$rc" -ne 0 ]; then
        cat artifacts/hostproto_report.json
        echo "run_tier1 --lint: shipped tree is not hostproto-clean" >&2
        exit "$rc"
    fi
    env JAX_PLATFORMS=cpu python -m tpu_dp.analysis conc --json \
        > artifacts/concurrency_report.json
    rc=$?
    if [ "$rc" -ne 0 ]; then
        cat artifacts/concurrency_report.json
        echo "run_tier1 --lint: shipped tree is not concurrency-clean" >&2
        exit "$rc"
    fi
    SCRATCH=$(mktemp -d /tmp/tpu_dp_lint_scratch.XXXXXX) || exit 1
    mkdir -p "$SCRATCH/scratchpkg"
    : > "$SCRATCH/scratchpkg/__init__.py"
    cp tests/fixtures/dplint/host/dp401_unrouted_io.py \
        "$SCRATCH/scratchpkg/ledger.py"
    if env JAX_PLATFORMS=cpu python -m tpu_dp.analysis host "$SCRATCH" \
        > /dev/null; then
        echo "run_tier1 --lint: planted DP401 fixture did NOT fail the" \
             "gate — the lint lane is toothless" >&2
        rm -rf "$SCRATCH"
        exit 1
    fi
    rm "$SCRATCH/scratchpkg/ledger.py"
    cp tests/fixtures/dplint/conc/dp501_unguarded_write.py \
        "$SCRATCH/scratchpkg/monitor.py"
    if env JAX_PLATFORMS=cpu python -m tpu_dp.analysis conc "$SCRATCH" \
        > /dev/null; then
        echo "run_tier1 --lint: planted DP501 fixture did NOT fail the" \
             "gate — the concurrency lane is toothless" >&2
        rm -rf "$SCRATCH"
        exit 1
    fi
    rm -rf "$SCRATCH"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'lint or conc' -p no:cacheprovider
fi

if [ "${1:-}" = "--obs" ]; then
    # 10-step smoke at obs=full on the CPU backend: proves the full
    # telemetry path end to end (per-step schema-2 records, heartbeats,
    # Perfetto export) and archives the artifacts CI reviewers diff.
    mkdir -p artifacts
    SMOKE=$(mktemp -d /tmp/tpu_dp_obs_smoke.XXXXXX) || exit 1
    env JAX_PLATFORMS=cpu python train.py \
        --data.dataset=synthetic --data.synthetic_train_size=40 \
        --data.synthetic_test_size=16 --data.batch_size=4 \
        --train.epochs=1 --train.log_every=5 --train.eval_at_end=false \
        --train.obs=full --train.ckpt_dir="$SMOKE/ck" || exit $?
    cp "$SMOKE/ck/metrics.jsonl" artifacts/metrics.jsonl || exit 1
    cp "$SMOKE/ck/obs/trace.perfetto.json" artifacts/trace.perfetto.json \
        || exit 1
    rm -rf "$SMOKE"
    echo "obs smoke: artifacts/metrics.jsonl + artifacts/trace.perfetto.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m obs \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--elastic" ]; then
    # The smoke is its own verdict (exit 1 when any survivor check
    # fails); the archived membership ledger + regroup report are the CI
    # record of the shrink. Then the full elastic suite, subprocess tests
    # included.
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python tools/elastic_smoke.py || exit $?
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m elastic \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--elastic-grow" ]; then
    # The smoke is its own verdict (exit 1 when any check of the
    # SIGTERM→relaunch→regrow round trip fails); the archived report and
    # timeline are the CI record of the grow. Then the full elastic
    # suite (grow protocol units, fencing, the 3-process relaunch
    # acceptance, and the joiner-crash fallback).
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python tools/elastic_grow_smoke.py || exit $?
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m elastic \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--guard" ]; then
    # Both smokes are their own verdict: train.py exits non-zero on any
    # guard failure, and the jq-free python checks pin the artifacts the
    # lane archives (quarantine records, rollback tombstones).
    mkdir -p artifacts
    SMOKE=$(mktemp -d /tmp/tpu_dp_guard_smoke.XXXXXX) || exit 1
    env JAX_PLATFORMS=cpu python train.py \
        --data.dataset=synthetic --data.synthetic_train_size=48 \
        --data.synthetic_test_size=16 --data.batch_size=4 \
        --train.epochs=1 --train.log_every=100 --train.eval_at_end=false \
        --train.steps_per_call=1 --parallel.num_devices=1 \
        --train.ckpt_dir="$SMOKE/skip" \
        --guard.enabled=true --guard.action=skip \
        --resilience.fault=nan:step=3 > "$SMOKE/skip.out" || exit $?
    env JAX_PLATFORMS=cpu python train.py \
        --data.dataset=synthetic --data.synthetic_train_size=128 \
        --data.synthetic_test_size=16 --data.batch_size=4 \
        --train.epochs=2 --train.log_every=100 --train.eval_at_end=false \
        --train.steps_per_call=1 --parallel.num_devices=1 \
        --train.ckpt_dir="$SMOKE/roll" --train.ckpt_async=false \
        --resilience.snapshot_every_steps=5 \
        --guard.enabled=true --guard.action=rollback \
        --guard.spike_min_steps=4 --guard.spike_z=12 \
        --resilience.fault=spike:step=8,scale=1e6 \
        > "$SMOKE/roll.out" || exit $?
    env JAX_PLATFORMS=cpu python - "$SMOKE" <<'PY' || exit 1
import json, sys
from pathlib import Path
smoke = Path(sys.argv[1])
skip = [json.loads(l) for l in (smoke/"skip/quarantine.jsonl").read_text().splitlines()]
assert [r["kind"] for r in skip] == ["quarantine"], skip
roll = [json.loads(l) for l in (smoke/"roll/quarantine.jsonl").read_text().splitlines()]
assert "tombstone" in [r["kind"] for r in roll], roll
report = {
    "skip": json.loads((smoke/"skip.out").read_text().strip().splitlines()[-1])["guard"],
    "rollback": json.loads((smoke/"roll.out").read_text().strip().splitlines()[-1])["guard"],
}
assert report["skip"]["quarantined"] == 1, report
assert report["rollback"]["rollbacks"] >= 1, report
out = Path("artifacts")
(out/"guard_report.json").write_text(json.dumps(report, indent=2) + "\n")
merged = (smoke/"skip/quarantine.jsonl").read_text() + (smoke/"roll/quarantine.jsonl").read_text()
(out/"quarantine.jsonl").write_text(merged)
print("guard smoke:", json.dumps(report))
PY
    rm -rf "$SMOKE"
    echo "guard smoke: artifacts/quarantine.jsonl + artifacts/guard_report.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m guard \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--obsctl" ]; then
    mkdir -p artifacts
    SMOKE=$(mktemp -d /tmp/tpu_dp_obsctl_smoke.XXXXXX) || exit 1
    # 1. The guard spike-rollback smoke, at obs=full: real rollback
    #    generations in metrics/quarantine/heartbeats + a black box.
    env JAX_PLATFORMS=cpu python train.py \
        --data.dataset=synthetic --data.synthetic_train_size=128 \
        --data.synthetic_test_size=16 --data.batch_size=4 \
        --train.epochs=2 --train.log_every=100 --train.eval_at_end=false \
        --train.steps_per_call=1 --parallel.num_devices=1 \
        --train.ckpt_dir="$SMOKE/roll" --train.ckpt_async=false \
        --train.obs=full \
        --resilience.snapshot_every_steps=5 \
        --guard.enabled=true --guard.action=rollback \
        --guard.spike_min_steps=4 --guard.spike_z=12 \
        --resilience.fault=spike:step=8,scale=1e6 \
        > "$SMOKE/roll.out" || exit $?
    # 2. The elastic kill-one-rank smoke, run dir pinned for obsctl.
    env JAX_PLATFORMS=cpu TPU_DP_SMOKE_DIR="$SMOKE/elastic" \
        python tools/elastic_smoke.py || exit $?
    # 3. obsctl over nothing but the artifact directories.
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs timeline "$SMOKE/roll" \
        --json --steps > artifacts/obsctl_timeline.json || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs timeline \
        "$SMOKE/elastic/ck" --json --steps \
        > artifacts/obsctl_timeline_elastic.json || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs merge-trace "$SMOKE/roll" \
        -o artifacts/obsctl_trace.json || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs diff "$SMOKE/roll" \
        --write-baseline "$SMOKE/base.json" || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs diff "$SMOKE/roll" \
        --baseline "$SMOKE/base.json" --json \
        > "$SMOKE/diff_clean.json" || exit $?
    # The gate must also TRIP: a tampered baseline (10x tighter p95)
    # has to exit nonzero, or the diff is a rubber stamp.
    env JAX_PLATFORMS=cpu python - "$SMOKE" <<'PY' || exit 1
import json, subprocess, sys
from pathlib import Path
smoke = Path(sys.argv[1])
base = json.loads((smoke / "base.json").read_text())
assert base["goodput"] is not None and base["p95_ms"] is not None, base
tampered = dict(base, p95_ms=base["p95_ms"] / 10.0)
(smoke / "tampered.json").write_text(json.dumps(tampered))
rc = subprocess.run(
    [sys.executable, "-m", "tpu_dp.obs", "diff", str(smoke / "roll"),
     "--baseline", str(smoke / "tampered.json")],
    capture_output=True, text=True,
).returncode
assert rc == 1, f"tampered baseline must exit 1, got {rc}"
timeline = json.loads(Path("artifacts/obsctl_timeline.json").read_text())
kinds = [e["kind"] for e in timeline["events"]]
assert "guard_rollback" in kinds and "exit" in kinds, kinds[:20]
steps = [e["step"] for e in timeline["events"] if e["kind"] == "step"]
assert len(steps) == len(set(steps)), "duplicate replayed-step events"
el = json.loads(Path("artifacts/obsctl_timeline_elastic.json").read_text())
el_kinds = [e["kind"] for e in el["events"]]
assert "eviction" in el_kinds and "elastic_regroup" in el_kinds, el_kinds[:20]
report = {
    "ok": True,
    "rollback_timeline_events": len(kinds),
    "elastic_timeline_events": len(el_kinds),
    "distinct_steps": timeline["stats"]["steps"],
    "diff_clean": json.loads((smoke / "diff_clean.json").read_text()),
    "diff_tampered_exit": rc,
}
Path("artifacts/obsctl_report.json").write_text(
    json.dumps(report, indent=2) + "\n")
print("obsctl lane:", json.dumps(report)[:300])
PY
    rm -rf "$SMOKE"
    echo "obsctl lane: artifacts/obsctl_report.json + obsctl_timeline*.json + obsctl_trace.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m obs \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--commprof" ]; then
    # Comm-attribution lane (docs/OBSERVABILITY.md "Comm/compute
    # attribution"): the smoke run captures an in-run profile window on
    # the sharded update and the checks below are the acceptance bar —
    # exact trace-vs-fingerprint collective reconciliation, wire-byte
    # agreement with the codec's own accounting, and the watch gate
    # tripping on a tampered stream while passing the clean one.
    mkdir -p artifacts
    SMOKE=$(mktemp -d /tmp/tpu_dp_commprof_smoke.XXXXXX) || exit 1
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python train.py \
        --data.dataset=synthetic --data.synthetic_train_size=80 \
        --data.synthetic_test_size=16 --data.batch_size=8 \
        --data.device_resident=off \
        --train.epochs=1 --train.log_every=5 --train.eval_at_end=false \
        --train.steps_per_call=1 --train.obs=full \
        --train.update_sharding=sharded \
        --train.ckpt_dir="$SMOKE/ck" \
        --obs.comm_profile_steps=4:6 || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs diff "$SMOKE/ck" \
        --write-baseline "$SMOKE/base.json" || exit $?
    # Per-record goodput rules would trip on the compile steps of any
    # short smoke (data_wait includes the first window's compile), so
    # the clean gate watches the comm + liveness signals.
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs watch "$SMOKE/ck" --replay \
        --baseline "$SMOKE/base.json" \
        --rule 'exposed_comm_ms>1.5*baseline' \
        --rule 'straggler_ratio>10' || exit $?
    env JAX_PLATFORMS=cpu python - "$SMOKE" <<'PY' || exit 1
import json, shutil, subprocess, sys
from pathlib import Path
smoke = Path(sys.argv[1])
rep = json.loads((smoke/"ck/obs/comm_report.json").read_text())
assert rep["schema"] == 1, rep["schema"]
recon = rep["reconciliation"]
assert recon["ok"], recon          # collective-count-vs-fingerprint
for kind, blk in recon["by_kind"].items():
    assert blk["ok"], (kind, blk)
assert {"reduce-scatter", "all-gather", "all-reduce"} <= set(recon["by_kind"])
assert rep["wire"]["reconciliation"]["ok"], rep["wire"]
assert rep["comm_ms"] > 0 and rep["compute_ms"] > 0, rep
ev = [json.loads(l) for l in (smoke/"ck/metrics.jsonl").read_text().splitlines()]
comm_events = [r for r in ev if r.get("event") == "comm_profile"]
assert len(comm_events) == 1 and comm_events[0]["reconciled"] is True
# The watch gate must also TRIP: replay a TAMPERED copy of the stream
# (an injected exposed-comm regression) — exit 1, or the live alert
# surface is a rubber stamp.
tampered = smoke / "tampered"
shutil.copytree(smoke / "ck", tampered)
bad = dict(comm_events[0])
bad["exposed_comm_ms"] = bad["exposed_comm_ms"] * 100 + 100
with open(tampered / "metrics.jsonl", "a") as f:
    f.write(json.dumps(bad) + "\n")
rc = subprocess.run(
    [sys.executable, "-m", "tpu_dp.obs", "watch", str(tampered),
     "--replay", "--baseline", str(smoke/"base.json"),
     "--rule", "exposed_comm_ms>1.5*baseline"],
    capture_output=True, text=True,
).returncode
assert rc == 1, f"tampered stream must trip watch (exit 1), got {rc}"
Path("artifacts/comm_report.json").write_text(
    json.dumps(rep, indent=2) + "\n")
print("commprof smoke:", json.dumps({
    "comm_ms": rep["comm_ms"], "exposed_comm_ms": rep["exposed_comm_ms"],
    "overlap_frac": rep["overlap_frac"],
    "reconciled": recon["ok"], "watch_tampered_exit": rc,
}))
PY
    rm -rf "$SMOKE"
    echo "commprof lane: artifacts/comm_report.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m commprof \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--overlap" ]; then
    # Bucketed-overlap lane (docs/PERF.md "Overlapped collectives"): the
    # acceptance bar of the train.bucket_mb schedule — the capture window
    # must reconcile exactly K bucketed exchanges per step against the
    # DP304 fingerprint schedule, the per-bucket wire bytes must be
    # byte-exact vs quant.wire_report, obs.overlap_frac must publish, and
    # the diff gate must TRIP against a tampered single-bucket baseline.
    mkdir -p artifacts
    SMOKE=$(mktemp -d /tmp/tpu_dp_overlap_smoke.XXXXXX) || exit 1
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python train.py \
        --data.dataset=synthetic --data.synthetic_train_size=80 \
        --data.synthetic_test_size=16 --data.batch_size=8 \
        --data.device_resident=off \
        --train.epochs=1 --train.log_every=5 --train.eval_at_end=false \
        --train.steps_per_call=1 --train.obs=full \
        --train.update_sharding=sharded --train.collective_dtype=int8 \
        --train.bucket_mb=0.05 \
        --train.ckpt_dir="$SMOKE/ck" \
        --obs.comm_profile_steps=4:6 || exit $?
    env JAX_PLATFORMS=cpu python - "$SMOKE" <<'LANEPY' || exit 1
import json, subprocess, sys
from pathlib import Path

import numpy as np

smoke = Path(sys.argv[1])
rep = json.loads((smoke / "ck/obs/comm_report.json").read_text())
assert rep["schema"] == 1, rep["schema"]
recon = rep["reconciliation"]
assert recon["ok"], recon

# K from the SAME plan the compiled schedule derives (the single source
# of truth): each quantizing bucket is one int8-payload all-to-all + one
# f32-scales all-to-all per step; plain buckets one reduce-scatter.
import jax
from tpu_dp.models import build_model
from tpu_dp.parallel import bucketing
from tpu_dp.train import SGD, create_train_state, shard_optimizer
model = build_model("net")
state = create_train_state(model, jax.random.PRNGKey(0),
                           np.zeros((1, 32, 32, 3), np.float32),
                           shard_optimizer(SGD(momentum=0.9), 8))
plan = bucketing.plan_for_tree(state.params, 8,
                               bucketing.parse_bucket_mb(0.05),
                               block_size=256, int8=True)
K = len(plan)
assert K > 1, f"bucket plan collapsed to {K} bucket(s) — no overlap to prove"
exp_a2a = 2 * sum(1 for b in plan if b.quantizes)
exp_rs = sum(1 for b in plan if not b.quantizes)
got_a2a = recon["by_kind"].get("all-to-all", {}).get("per_step_observed", 0)
got_rs = recon["by_kind"].get("reduce-scatter", {}).get("per_step_observed", 0)
assert got_a2a == exp_a2a, (got_a2a, exp_a2a)
assert got_rs == exp_rs, (got_rs, exp_rs)
for kind, blk in recon["by_kind"].items():
    assert blk["ok"], (kind, blk)
# Per-bucket wire bytes byte-exact vs the codec's own accounting.
wire = rep["wire"]["reconciliation"]
assert wire["ok"] and wire["dtype"] == "int8", rep["wire"]
assert rep["overlap_frac"] is not None and rep["comm_ms"] > 0, rep
# The input-side half: obs.goodput and obs.overlap_frac both published.
recs = [json.loads(l) for l in
        (smoke / "ck/metrics.jsonl").read_text().splitlines()]
counters = [r.get("counters", {}) for r in recs if r.get("counters")]
assert any("obs.goodput" in c for c in counters), "obs.goodput never published"
assert any("obs.overlap_frac" in c for c in counters), \
    "obs.overlap_frac never published"

# The gate proof: a TAMPERED single-bucket baseline claiming near-zero
# exposed comm must make `obsctl diff` exit 1 — otherwise the overlap
# numbers are decorative, not gating.
rc0 = subprocess.run(
    [sys.executable, "-m", "tpu_dp.obs", "diff", str(smoke / "ck"),
     "--write-baseline", str(smoke / "base.json")]).returncode
assert rc0 == 0, f"clean self-baseline diff must exit 0, got {rc0}"
base = json.loads((smoke / "base.json").read_text())
base["exposed_comm_ms"] = max(1e-6, base["exposed_comm_ms"] / 100.0)
base["overlap_frac"] = 0.999
(smoke / "tampered_base.json").write_text(json.dumps(base))
rc = subprocess.run(
    [sys.executable, "-m", "tpu_dp.obs", "diff", str(smoke / "ck"),
     "--baseline", str(smoke / "tampered_base.json")],
    capture_output=True, text=True).returncode
assert rc == 1, f"tampered single-bucket baseline must exit 1, got {rc}"

Path("artifacts/overlap_report.json").write_text(json.dumps({
    "ok": True,
    "buckets": K,
    "per_step_all_to_all": got_a2a,
    "per_step_reduce_scatter": got_rs,
    "comm_ms": rep["comm_ms"],
    "exposed_comm_ms": rep["exposed_comm_ms"],
    "overlap_frac": rep["overlap_frac"],
    "wire_reconciled": wire["ok"],
    "diff_tampered_exit": rc,
    "comm_report": rep,
}, indent=2) + "\n")
print("overlap smoke:", json.dumps({
    "buckets": K, "overlap_frac": rep["overlap_frac"],
    "reconciled": recon["ok"], "diff_tampered_exit": rc,
}))
LANEPY
    rm -rf "$SMOKE"
    echo "overlap lane: artifacts/overlap_report.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m overlap \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--quant" ]; then
    # Quantized-collectives lane (docs/PERF.md "Quantized collectives"):
    # a BENCH point through the real int8 wire path on the 8-virtual-
    # device CPU mesh, exit-coded checks on its quant block (the wire
    # byte accounting must show real compression and a clean overflow
    # count), archived as artifacts/quant_report.json — then the -m quant
    # suite (codec units, the f32/bf16/int8 parity harness, the
    # error-feedback ablation, guard/NaN interaction, checkpoint
    # resharding + kill/resume, analyzer rules, obsctl gating).
    mkdir -p artifacts
    env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python bench.py --platform cpu --model resnet18 \
        --per-chip-batch 8 --measure-steps 3 --steps-per-call 1 \
        --latency-steps 4 --update-sharding sharded \
        --collective-dtype int8 --point-timeout 420 \
        > /tmp/_quant_bench.out || exit $?
    env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json
from pathlib import Path
rec = None
for line in reversed(Path("/tmp/_quant_bench.out").read_text().splitlines()):
    line = line.strip()
    if line.startswith("{"):
        rec = json.loads(line)
        break
assert rec and rec.get("value"), rec
q = rec.get("quant")
assert q, "BENCH record has no quant block"
b = q["wire_bytes_per_step"]
assert b["int8"] < b["bf16"] < b["f32"], b
assert q["compression_vs_f32"] > 3.0, q
assert q["overflow"] == 0, f"non-finite blocks in a clean run: {q}"
assert q["stats_steps"] > 0 and "clip_blocks" in q, q
assert rec["config"]["collective_dtype"] == "int8", rec["config"]
assert rec["latency"]["n_steps"] > 0, rec
report = {
    "ok": True,
    "metric": rec["metric"],
    "value": rec["value"],
    "backend": rec["backend"],
    "latency": rec["latency"],
    "quant": q,
    "config": rec["config"],
}
Path("artifacts/quant_report.json").write_text(
    json.dumps(report, indent=2) + "\n")
print("quant smoke:", json.dumps({"compression_vs_f32":
      q["compression_vs_f32"], "overflow": q["overflow"],
      "clip_blocks": q["clip_blocks"]}))
PY
    echo "quant smoke: artifacts/quant_report.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m quant \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--tune" ]; then
    # Self-tuning lane (docs/TUNE.md): the whole tentpole end-to-end on
    # the 8-virtual-device CPU mesh. A no-auto 3-point bucket ladder
    # keeps the search to three fenced trials + two chaos-gate trials
    # (the planted fabricated top against a tampered oracle — must be
    # rejected — then the real winner). Everything downstream is
    # exit-coded: validate re-earns the claims, a cached re-search must
    # reproduce the profile byte-for-byte, a hand-edited claims block
    # must flunk validation, and a mis-keyed profile must be refused by
    # bench.py before it measures anything.
    mkdir -p artifacts
    TUNE=$(mktemp -d /tmp/tpu_dp_tune.XXXXXX) || exit 1
    TUNE_SPACE='train.update_sharding=sharded;train.collective_dtype=int8;train.quant_block_size=64;train.bucket_mb=0.0,0.25,1.0'
    env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m tpu_dp.tune --seed 20260806 --budget tiny \
        --space "$TUNE_SPACE" --platform cpu --per-chip-batch 2 \
        --plant-fragile --workdir "$TUNE" \
        --out "$TUNE/tuned.json" || exit $?
    # Bitwise reproduction: the same (seed, ledger) must re-derive the
    # profile without running a single subprocess.
    env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m tpu_dp.tune --seed 20260806 --budget tiny \
        --space "$TUNE_SPACE" --platform cpu --per-chip-batch 2 \
        --plant-fragile --workdir "$TUNE" \
        --out "$TUNE/tuned_replay.json" || exit $?
    cmp "$TUNE/tuned.json" "$TUNE/tuned_replay.json" || {
        echo "tune lane: cached re-search is not byte-identical"; exit 1; }
    env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m tpu_dp.tune validate --profile "$TUNE/tuned.json" \
        --platform cpu --out artifacts/tune_validate.json || exit $?
    env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python - "$TUNE" <<'PY' || exit 1
import json, subprocess, sys
from pathlib import Path
from tpu_dp.tune.profile import (build_profile, dump_profile, load_profile,
                                 make_key)
tune = Path(sys.argv[1])
prof = load_profile(tune / "tuned.json")  # schema/key/hash all validated
assert prof["key"] == {"workload": "resnet18", "devices": 8,
                       "backend": "cpu", "device_kind": "cpu"}, prof["key"]
assert prof["provenance"]["grid_points"] == 3, prof["provenance"]
assert len(prof["provenance"]["trial_sequence"]) == 3
gate = prof["chaos_gate"]
assert gate["verdict"]["ok"], gate
rej = gate["rejected"]
assert len(rej) == 1 and rej[0]["synthesized"], rej  # the planted top
assert "block333" in rej[0]["label"], rej
assert rej[0]["claimed_score"] > prof["objective"]["value"], rej
assert prof["claims"]["img_per_sec_per_chip"] > 0, prof["claims"]
assert prof["claims"]["exposed_comm_ms"] is not None, prof["claims"]
val = json.loads(Path("artifacts/tune_validate.json").read_text())
assert not val["verdict"]["regressed"] and val["verdict"]["compared"] >= 1
# A hand-edited claims block (the knobs untouched, so config_hash still
# verifies) must flunk re-validation: claims are earned, not asserted.
tampered = json.loads((tune / "tuned.json").read_text())
tampered["claims"]["img_per_sec_per_chip"] *= 10
tampered["claims"]["goodput"] = (tampered["claims"].get("goodput") or 1) * 10
(tune / "tampered.json").write_text(json.dumps(tampered))
proc = subprocess.run(
    [sys.executable, "-m", "tpu_dp.tune", "validate",
     "--profile", str(tune / "tampered.json"), "--platform", "cpu"],
    capture_output=True, text=True)
assert proc.returncode == 1, (
    f"tampered claims must exit 1, got {proc.returncode}\n"
    + proc.stdout[-2000:] + proc.stderr[-2000:])
assert "REGRESSED" in proc.stdout + proc.stderr, proc.stdout[-2000:]
# A profile keyed for a backend this host does not have must be a typed
# bench.py refusal (exit 2) BEFORE any measurement — never a silent
# CPU-number fallback wearing a TPU profile's claims.
dump_profile(build_profile(
    key=make_key("resnet18", 8, "tpu", "v4"),
    knobs=dict(prof["config"]), claims=dict(prof["claims"]),
    objective=dict(prof["objective"]), provenance={"seed": 0}),
    tune / "tpu_keyed.json")
proc = subprocess.run(
    [sys.executable, "bench.py", "--profile", str(tune / "tpu_keyed.json"),
     "--platform", "cpu", "--measure-steps", "1", "--latency-steps", "2"],
    capture_output=True, text=True)
assert proc.returncode == 2, (
    f"mis-keyed profile must exit 2, got {proc.returncode}\n"
    + proc.stdout[-2000:] + proc.stderr[-2000:])
assert "keyed for" in proc.stdout + proc.stderr, proc.stdout[-2000:]
report = {
    "ok": True,
    "config_hash": prof["config_hash"],
    "objective": prof["objective"],
    "claims": prof["claims"],
    "planted_rejection": rej[0],
    "validate": val["verdict"],
    "tampered_claims_exit": 1,
    "miskeyed_bench_exit": 2,
}
Path("artifacts/tune_report.json").write_text(
    json.dumps(report, indent=2) + "\n")
Path("artifacts/tuned.json").write_bytes(
    (tune / "tuned.json").read_bytes())
print("tune lane:", json.dumps({
    "crowned": prof["config_hash"],
    "objective": prof["objective"]["value"],
    "planted_rejected": rej[0]["label"],
}))
PY
    rm -rf "$TUNE"
    echo "tune lane: artifacts/tune_report.json + artifacts/tuned.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m tune \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--chaos" ]; then
    # The harness is its own verdict (exit 1 on the first trial whose
    # invariants go red, after shrinking to a minimal repro spec); the
    # archived report is the CI record of which schedules were attacked.
    # The pinned seed's 5 trials (replay `Random(f"20260809:{i}")`):
    # spike rollback, kill;torn (death composed with a post-commit torn
    # write — the relaunch-remainder path), ioerr, delay, bitrot;ioerr
    # — write-fault DEGRADE teeth, checksum fallback, and the guard
    # interaction all exercised every CI pass (docs/CHAOS.md).
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python -m tpu_dp.chaos --seed 20260809 \
        --trials 5 --out artifacts/chaos_report.json || exit $?
    # The gate must also TRIP: a tampered oracle has to exit nonzero
    # with a minimized repro spec, or the auditor is a rubber stamp.
    env JAX_PLATFORMS=cpu python - <<'PY' || exit 1
import json, subprocess, sys
from pathlib import Path
rep = json.loads(Path("artifacts/chaos_report.json").read_text())
assert rep["ok"] and len(rep["trials"]) == 5, rep
assert all(t["ok"] for t in rep["trials"]), rep
proc = subprocess.run(
    [sys.executable, "-m", "tpu_dp.chaos", "--seed", "20260809",
     "--trials", "1", "--tamper-oracle"],
    capture_output=True, text=True,
)
assert proc.returncode == 1, (
    f"tampered oracle must exit 1, got {proc.returncode}\n"
    + proc.stdout[-2000:] + proc.stderr[-2000:])
assert "minimal reproducing spec" in proc.stdout, proc.stdout[-2000:]
print("chaos lane:", json.dumps({
    "trials": len(rep["trials"]), "ok": rep["ok"],
    "specs": [t["spec"] for t in rep["trials"]],
    "tamper_oracle_exit": proc.returncode,
}))
PY
    echo "chaos lane: artifacts/chaos_report.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--serve-elastic" ]; then
    # The smoke is its own verdict (python -m tpu_dp.serve exits 1 on any
    # book mismatch, retrace, or attainment-floor miss); the python block
    # then pins the chaos artifacts: typed shed reasons, both weight
    # versions served, the membership ledger's drain+rejoin epochs, the
    # obsctl timeline kinds, and the serve diff gate in both directions.
    mkdir -p artifacts
    SMOKE=$(mktemp -d /tmp/tpu_dp_serve_el.XXXXXX) || exit 1
    env JAX_PLATFORMS=cpu python -m tpu_dp.serve \
        --replicas 2 --requests 280 --pattern burst --burst 12 \
        --rate-rps 400 --buckets 1,2,4,8 --max-wait-ms 2 \
        --slo-ms 3000 --class-mix 0.6,0.4 --class-slo-ms 3000,6000 \
        --floors 0:0.9 --stale-after-s 0.3 \
        --fault "delay:step=3,ms=500,rank=0;leave:step=4,rank=1" \
        --rejoin-at 200:1 --swap-at 120 \
        --run-dir "$SMOKE/run" \
        --out artifacts/serve_elastic_report.json > /dev/null || exit $?
    cp artifacts/serve_elastic_report.json "$SMOKE/run/" || exit 1
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs timeline "$SMOKE/run" \
        --json > artifacts/serve_elastic_timeline.json || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs diff "$SMOKE/run" \
        --write-baseline "$SMOKE/base.json" || exit $?
    env JAX_PLATFORMS=cpu python -m tpu_dp.obs diff "$SMOKE/run" \
        --baseline "$SMOKE/base.json" > /dev/null || exit $?
    env JAX_PLATFORMS=cpu python - "$SMOKE" <<'PY' || exit 1
import json, subprocess, sys
from pathlib import Path
smoke = Path(sys.argv[1])
rep = json.loads(Path("artifacts/serve_elastic_report.json").read_text())
assert rep["verdict"]["ok"] and rep["consistent"], rep["verdict"]
t = rep["ground_truth"]
known = {"queue_full", "deadline", "closed", "replica_failed"}
assert set(t["shed_by_reason"]) <= known, t["shed_by_reason"]
assert t["completed"] + t["shed"] + t["unresolved"] == t["submitted"]
assert set(t["served_by_version"]) == {"1", "2"}, t["served_by_version"]
assert rep["classes"]["0"]["attainment"] >= 0.9, rep["classes"]
assert rep["membership_epoch"] == 2, rep["membership_epoch"]  # leave+rejoin
led = sorted(p.name for p in (smoke/"run/membership/serve").glob("epoch_*"))
assert len(led) == 3, led
tl = json.loads(Path("artifacts/serve_elastic_timeline.json").read_text())
kinds = [e["kind"] for e in tl["events"]]
for k in ("membership_formed", "serve_dispatch", "replica_drain",
          "eviction", "model_swap", "replica_rejoin", "membership_epoch"):
    assert k in kinds, (k, sorted(set(kinds)))
# The gate must also TRIP: a tampered baseline demanding impossible
# class-0 attainment has to exit 1, or the diff is a rubber stamp.
base = json.loads((smoke/"base.json").read_text())
assert base["serve_attainment_c0"] is not None, base
tampered = dict(base, serve_attainment_c0=1.5)
(smoke/"tampered.json").write_text(json.dumps(tampered))
rc = subprocess.run(
    [sys.executable, "-m", "tpu_dp.obs", "diff", str(smoke/"run"),
     "--baseline", str(smoke/"tampered.json")],
    capture_output=True, text=True,
).returncode
assert rc == 1, f"tampered baseline must exit 1, got {rc}"
print("serve-elastic smoke:", json.dumps({
    "completed": t["completed"], "shed": t["shed_by_reason"],
    "versions": t["served_by_version"],
    "attainment_c0": rep["classes"]["0"]["attainment"],
    "timeline_events": len(kinds), "diff_tampered_exit": rc,
}))
PY
    rm -rf "$SMOKE"
    echo "serve-elastic smoke: artifacts/serve_elastic_report.json + serve_elastic_timeline.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serve \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--fleet" ]; then
    # The smoke is its own verdict (exit 1 when either rule fails to trip
    # on the poisoned run, the attribution names the wrong rank, or the
    # clean twin alerts); the archived report is the CI record of the
    # skew numbers both runs produced.
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python tools/fleet_smoke.py || exit $?
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m fleet \
        -p no:cacheprovider
fi

if [ "${1:-}" = "--serve" ]; then
    # The serve smoke is its own verdict (exit 1 when the loadgen ground
    # truth and the serve counters disagree, or any bucket program
    # retraced after warmup); the report is the CI artifact reviewers
    # diff for SLO-attainment / shed-count regressions.
    mkdir -p artifacts
    env JAX_PLATFORMS=cpu python -m tpu_dp.serve --requests 200 \
        --out artifacts/serve_report.json > /dev/null || exit $?
    echo "serve smoke: artifacts/serve_report.json"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serve \
        -p no:cacheprovider
fi

rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit "$rc"
