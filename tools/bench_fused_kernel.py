"""Microbenchmark: fused Pallas conv kernel vs XLA's unfused chain.

Measures the op this kernel replaces — BN-apply + ReLU (+residual) + 3x3
stride-1 conv (`tpu_dp/ops/conv_block.py`) — at each ResNet stage's shape,
against the XLA statement of the same math, on whatever backend is up
(intended: the real TPU chip; falls back to interpret-mode on CPU, which
is a correctness run, not a perf number).

Prints one JSON line per point:
  {"shape": [B,H,W,C], "block_b": n, "impl": "pallas"|"xla",
   "ms": t, "tflops": f, "pct_peak": p}

Usage:
  python tools/bench_fused_kernel.py                 # stage shapes, b2048
  python tools/bench_fused_kernel.py --batch 1024 --stages 0 --block-b 4,8
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from tpu_dp.ops.conv_block import (
    fused_affine_relu_conv,
    reference_affine_relu_conv,
)

BF16_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # per chip
}

# CIFAR ResNet-18 stage shapes (H=W spatial, C channels at stride-1 blocks).
STAGE_SHAPES = {0: (32, 64), 1: (16, 128), 2: (8, 256), 3: (4, 512)}


def _fence(y):
    # On the axon relay, block_until_ready can return early; fetching a
    # scalar is the reliable completion fence (docs/DESIGN.md).
    float(jnp.sum(y[0, 0, 0]))
    y.block_until_ready()


def timeit(f, *args, iters=20):
    y = f(*args)
    _fence(y)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(*args)
    _fence(y)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--stages", default="0,1,2,3")
    ap.add_argument("--block-b", default="4,8,16")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--with-residual", action="store_true")
    ap.add_argument("--platform", default=None, choices=["cpu"],
                    help="force cpu (interpret-mode correctness run; the "
                         "env's sitecustomize pins the tpu backend, so the "
                         "env var alone is not enough)")
    args = ap.parse_args()

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    peak = BF16_PEAK_FLOPS.get(dev.device_kind)
    stages = [int(s) for s in args.stages.split(",")]
    blocks = [int(b) for b in args.block_b.split(",")]

    for stage in stages:
        hw, c = STAGE_SHAPES[stage]
        shape = (args.batch, hw, hw, c)
        ks = jax.random.split(jax.random.PRNGKey(stage), 5)
        x = jax.random.normal(ks[0], shape, jnp.bfloat16)
        w = (jax.random.normal(ks[1], (3, 3, c, c)) * 0.1).astype(jnp.float32)
        scale = jax.random.normal(ks[2], (c,)) * 0.5 + 1.0
        shift = jax.random.normal(ks[3], (c,)) * 0.1
        res = (jax.random.normal(ks[4], shape, jnp.bfloat16)
               if args.with_residual else None)
        flops = 2 * args.batch * hw * hw * c * c * 9

        def emit(impl, block_b, dt):
            rec = {"shape": list(shape), "block_b": block_b, "impl": impl,
                   "ms": round(dt * 1e3, 3),
                   "tflops": round(flops / dt / 1e12, 1),
                   "pct_peak": (round(100 * flops / dt / peak, 1)
                                if peak else None),
                   "residual": args.with_residual,
                   "device": dev.device_kind}
            print(json.dumps(rec), flush=True)

        ref = jax.jit(lambda x, w, r: reference_affine_relu_conv(
            x, w, scale, shift, r))
        emit("xla", 0, timeit(ref, x, w, res, iters=args.iters))

        for bb in blocks:
            try:
                f = jax.jit(functools.partial(
                    fused_affine_relu_conv, block_b=bb))
                dt = timeit(f, x, w, scale, shift, res, iters=args.iters)
                emit("pallas", bb, dt)
            except Exception as e:
                print(json.dumps({"shape": list(shape), "block_b": bb,
                                  "impl": "pallas",
                                  "error": f"{type(e).__name__}: {e}"[:200]}),
                      flush=True)


if __name__ == "__main__":
    main()
