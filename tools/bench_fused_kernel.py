"""Microbenchmark: fused Pallas conv kernel vs XLA's unfused chain.

Measures the op this kernel replaces — BN-apply + ReLU (+residual) + 3x3
stride-1 conv (`tpu_dp/ops/conv_block.py`) — at each ResNet stage's shape,
against the XLA statement of the same math, on whatever backend is up
(intended: the real TPU chip; falls back to interpret-mode on CPU, which
is a correctness run, not a perf number).

Prints one JSON line per point:
  {"shape": [B,H,W,C], "block_b": n, "impl": "pallas"|"xla",
   "ms": t, "tflops": f, "pct_peak": p}

Usage:
  python tools/bench_fused_kernel.py                 # stage shapes, b2048
  python tools/bench_fused_kernel.py --batch 1024 --stages 0 --block-b 4,8
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from tpu_dp.ops.conv_block import (
    fused_affine_relu_conv,
    reference_affine_relu_conv,
)

BF16_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # per chip
}

# CIFAR ResNet-18 stage shapes (H=W spatial, C channels at stride-1 blocks).
STAGE_SHAPES = {0: (32, 64), 1: (16, 128), 2: (8, 256), 3: (4, 512)}


def _fence(y):
    # On the axon relay, block_until_ready can return early; fetching a
    # scalar is the reliable completion fence (docs/DESIGN.md). Fencing
    # every leaf keeps XLA from dead-code-eliminating any grad output.
    for leaf in jax.tree_util.tree_leaves(y):
        float(leaf.reshape(-1)[0])
        leaf.block_until_ready()


def timeit(f, *args, iters=20):
    y = f(*args)
    _fence(y)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(*args)
    _fence(y)
    return (time.perf_counter() - t0) / iters


def check_shard_map(batch: int) -> int:
    """On-chip pin of the real (non-interpret) kernel under `jax.shard_map`.

    Off-TPU the per-shard code takes the XLA fallback
    (`tpu_dp/ops/_partition.py:shard_map_interp`), so the CPU suite can
    never reach the kernel *body* under shard_map — this check runs it on
    a real TPU mesh and compares against the GSPMD path and the XLA
    oracle (expected bit-identical: same f32 affine, same bf16 rounding,
    same f32 conv accumulation). Returns a process exit code.
    """
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_dp.ops.conv_block import fused_conv_bn

    if jax.default_backend() != "tpu":
        print(json.dumps({"check": "shard_map_fused", "skipped": True,
                          "reason": f"backend is {jax.default_backend()}, "
                                    "not tpu (fallback path would run)"}))
        return 0

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("data",))
    hw, c = STAGE_SHAPES[0]
    ks = jax.random.split(jax.random.PRNGKey(7), 4)
    x = jax.random.normal(ks[0], (batch, hw, hw, c), jnp.bfloat16)
    w = (jax.random.normal(ks[1], (3, 3, c, c)) * 0.1).astype(jnp.float32)
    scale = jax.random.normal(ks[2], (c,)) * 0.5 + 1.0
    shift = jax.random.normal(ks[3], (c,)) * 0.1
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    specs = (P("data"), P(None, None, None, None), P(None), P(None))

    failures = 0

    def compare(name, a, b, atol=0.0):
        # Kernel-vs-kernel paths (shard_map vs GSPMD run the same Pallas
        # program) must match bitwise; the kernel-vs-XLA-oracle pair is
        # allowed bf16-ulp accumulation-order noise, same as
        # tests/test_conv_block.py's atol.
        nonlocal failures
        diff = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32))))
        ok = diff <= atol
        failures += not ok
        print(json.dumps({"check": f"shard_map_fused/{name}",
                          "max_abs_diff": diff, "atol": atol, "ok": ok,
                          "n_devices": int(devs.size),
                          "device": jax.devices()[0].device_kind}),
              flush=True)

    # Forward: shard_map kernel vs GSPMD kernel vs XLA oracle.
    gspmd = jax.jit(lambda x, w, s, b: fused_affine_relu_conv(x, w, s, b,
                                                              None))
    smap = jax.jit(jax.shard_map(
        lambda x, w, s, b: fused_affine_relu_conv(x, w, s, b, None),
        mesh=mesh, in_specs=specs, out_specs=P("data")))
    ref = jax.jit(lambda x, w, s, b: reference_affine_relu_conv(x, w, s, b))
    y_s = smap(xs, w, scale, shift)
    compare("fwd_vs_gspmd", y_s, gspmd(xs, w, scale, shift))
    compare("fwd_vs_xla", y_s, ref(xs, w, scale, shift), atol=5e-2)

    # Emit + stats variants (stats: per-shard partials psum'd to the
    # global sums the GSPMD partition rule produces).
    def smap_emit_stats(x, w, s, b):
        y, z, st = fused_conv_bn(x, w, s, b, None, emit_z=True)
        return y, z, jax.lax.psum(st, "data")

    smap_es = jax.jit(jax.shard_map(
        smap_emit_stats, mesh=mesh, in_specs=specs,
        out_specs=(P("data"), P("data"), P(None, None))))
    gspmd_es = jax.jit(lambda x, w, s, b: fused_conv_bn(x, w, s, b, None,
                                                        emit_z=True))
    ys, zs, sts = smap_es(xs, w, scale, shift)
    yg, zg, stg = gspmd_es(xs, w, scale, shift)
    compare("emit_y", ys, yg)
    compare("emit_z", zs, zg)
    compare("stats", sts, stg)

    # Backward (input grad), XLA conv-transpose and Pallas bwd variants:
    # d/dx_shard of the global sum == per-shard grad, no collective needed.
    for pallas_bwd in (False, True):
        def local_grad(x, w, s, b, pb=pallas_bwd):
            return jax.grad(lambda xi: jnp.sum(
                fused_affine_relu_conv(xi, w, s, b, None,
                                       pallas_bwd=pb).astype(jnp.float32)))(x)

        smap_g = jax.jit(jax.shard_map(local_grad, mesh=mesh,
                                       in_specs=specs, out_specs=P("data")))
        gspmd_g = jax.jit(local_grad)
        tag = "dx_pallas_bwd" if pallas_bwd else "dx"
        compare(tag, smap_g(xs, w, scale, shift),
                gspmd_g(xs, w, scale, shift))

    print(json.dumps({"check": "shard_map_fused", "failures": failures,
                      "ok": failures == 0}), flush=True)
    return 1 if failures else 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--stages", default="0,1,2,3")
    ap.add_argument("--block-b", default="4,8,16")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--with-residual", action="store_true")
    ap.add_argument("--grad", action="store_true",
                    help="time the full fwd+bwd (input+weight+affine "
                         "grads) instead of forward only — compares the "
                         "XLA backward against pallas_bwd variants")
    ap.add_argument("--check-shard-map", action="store_true",
                    help="instead of benchmarking, pin the real kernel "
                         "under jax.shard_map against the GSPMD path on a "
                         "TPU mesh (VERDICT r3 weak #3); exits 0 on match")
    ap.add_argument("--platform", default=None, choices=["cpu"],
                    help="force cpu (interpret-mode correctness run; the "
                         "env's sitecustomize pins the tpu backend, so the "
                         "env var alone is not enough)")
    args = ap.parse_args()

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if args.check_shard_map:
        sys.exit(check_shard_map(min(args.batch, 256)))
    dev = jax.devices()[0]
    peak = BF16_PEAK_FLOPS.get(dev.device_kind)
    stages = [int(s) for s in args.stages.split(",")]
    blocks = [int(b) for b in args.block_b.split(",")]

    for stage in stages:
        hw, c = STAGE_SHAPES[stage]
        shape = (args.batch, hw, hw, c)
        ks = jax.random.split(jax.random.PRNGKey(stage), 5)
        x = jax.random.normal(ks[0], shape, jnp.bfloat16)
        w = (jax.random.normal(ks[1], (3, 3, c, c)) * 0.1).astype(jnp.float32)
        scale = jax.random.normal(ks[2], (c,)) * 0.5 + 1.0
        shift = jax.random.normal(ks[3], (c,)) * 0.1
        res = (jax.random.normal(ks[4], shape, jnp.bfloat16)
               if args.with_residual else None)
        # fwd: one 3x3 conv; fwd+bwd adds the input-grad conv and the
        # weight-grad contraction (same contraction size each) ~= 3x.
        flops = 2 * args.batch * hw * hw * c * c * 9 * (3 if args.grad else 1)

        def emit(impl, block_b, dt):
            rec = {"shape": list(shape), "block_b": block_b, "impl": impl,
                   "ms": round(dt * 1e3, 3),
                   "tflops": round(flops / dt / 1e12, 1),
                   "pct_peak": (round(100 * flops / dt / peak, 1)
                                if peak else None),
                   "residual": args.with_residual, "grad": args.grad,
                   "device": dev.device_kind}
            print(json.dumps(rec), flush=True)

        def grad_of(op):
            # Full training-shaped backward: grads wrt every differentiable
            # operand (returning them all keeps XLA from DCE'ing any path).
            argnums = (0, 1, 2, 3) if res is None else (0, 1, 2, 3, 4)

            def f(x, w, scale, shift, res):
                def loss(*a):
                    y = op(*a)
                    return jnp.sum(y.astype(jnp.float32))
                return jax.grad(loss, argnums)(x, w, scale, shift, res)
            return f

        def run(impl, block_b, op):
            try:
                f = jax.jit(grad_of(op)) if args.grad else jax.jit(
                    lambda x, w, s, sh, r: op(x, w, s, sh, r))
                dt = timeit(f, x, w, scale, shift, res, iters=args.iters)
                emit(impl, block_b, dt)
            except Exception as e:
                print(json.dumps({"shape": list(shape), "block_b": block_b,
                                  "impl": impl, "grad": args.grad,
                                  "error": f"{type(e).__name__}: {e}"[:200]}),
                      flush=True)

        run("xla", 0, reference_affine_relu_conv)
        for bb in blocks:
            run("pallas", bb, functools.partial(
                fused_affine_relu_conv, block_b=bb))
            if args.grad:
                run("pallas+bwd", bb, functools.partial(
                    fused_affine_relu_conv, block_b=bb, pallas_bwd=True))


if __name__ == "__main__":
    main()
