#!/bin/bash
# Round-4 TPU capture watcher.
#
# The box reaches its one TPU v5e chip through a relay that wedges for
# hours and comes back in windows sometimes only minutes long (see
# benchmarks/longrun_r3/README.md).  This watcher turns that into
# captured measurements: it probes the chip with a tiny matmul in a
# timeout-wrapped subprocess, and the moment a probe succeeds it runs the
# queued measurement stages in priority order, each under its own
# timeout, checkpointing completion per stage so an interrupted window
# resumes where it left off.
#
# Stages live in benchmarks/r4_capture/stages.txt, one per line:
#   name|timeout_seconds|command...
# The file is re-read every loop, so new stages can be appended while the
# watcher runs.  A stage is skipped once benchmarks/r4_capture/<name>.done
# exists; stdout/stderr land in <name>.out / <name>.err.
#
# Usage:  bash tools/r4_watch.sh   (run in background; tail watch.log)
#
# Test hooks (tests/test_watcher.py): R4_CAPTURE_DIR overrides the
# capture dir, R4_PROBE_CMD replaces the TPU probe, R4_SLEEP_S the
# inter-probe sleep.

set -u
cd "$(dirname "$0")/.."
OUT="${R4_CAPTURE_DIR:-benchmarks/r4_capture}"
mkdir -p "$OUT"
STAGES="$OUT/stages.txt"
SLEEP_S="${R4_SLEEP_S:-120}"

# Persistent XLA-compile cache shared by every stage. Compiles go over
# the relay (PALLAS_AXON_REMOTE_COMPILE=1), so a stage killed by a
# mid-window wedge re-pays its whole compile budget on retry unless the
# executables are cached client-side. If the axon PjRt plugin doesn't
# support executable serialization this is a logged no-op; if it does,
# retries skip straight to the first uncompiled program.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-2}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }

# One watcher per capture dir: a later session starting its own instance
# must not race this one (two watchers double-running TPU stages through
# the one relay is exactly the contention that wedges it). Children run
# with fd 9 closed (9>&-) so the lock really does die with THIS process —
# a surviving stage child must not make a restarted watcher bow out.
exec 9>"$OUT/lock"
if ! flock -n 9; then
  log "another watcher holds $OUT/lock; exiting (pid $$)"
  exit 0
fi

probe() {
  if [ -n "${R4_PROBE_CMD:-}" ]; then
    timeout -k 10 90 bash -c "$R4_PROBE_CMD" >/dev/null 2>&1 9>&-
    return
  fi
  timeout -k 10 90 python - >/dev/null 2>&1 9>&- <<'EOF'
import jax, jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
assert float((x @ x).sum()) > 0
EOF
}

log "watcher started (pid $$)"
while :; do
  if [ -f "$OUT/pause" ]; then
    # Operator hook: `touch pause` idles the watcher (e.g. while running
    # chip work by hand), `rm pause` resumes.
    sleep "$SLEEP_S" 9>&-
    continue
  fi
  if probe; then
    log "probe ok"
    ran_any=0
    while IFS='|' read -r name to cmd || [ -n "${name:-}" ]; do
      [ -z "${name:-}" ] && continue
      case "$name" in \#*) continue ;; esac
      if [ -f "$OUT/$name.done" ]; then
        # Backfill: stages completed before the captured/ mirror existed
        # (or whose copy failed) still get preserved.
        if [ -f "$OUT/$name.out" ] && [ ! -f "$OUT/captured/$name.out" ]; then
          mkdir -p "$OUT/captured"
          cp "$OUT/$name.out" "$OUT/captured/$name.out" \
            || log "stage $name: mirror failed"
        fi
        continue
      fi
      attempts=$(cat "$OUT/$name.fail" 2>/dev/null || echo 0)
      [ "$attempts" -ge 3 ] && continue   # perma-failed; stop burning windows
      ran_any=1
      log "stage $name: starting (timeout ${to}s, attempt $((attempts + 1))/3): $cmd"
      if [ -f "$OUT/pause" ]; then
        log "paused mid-window; remaining stages deferred"
        break
      fi
      if timeout -k 30 "$to" bash -c "$cmd" >"$OUT/$name.out" 2>"$OUT/$name.err" 9>&-; then
        touch "$OUT/$name.done"
        # Mirror successful outputs into the tracked captured/ dir so an
        # end-of-session auto-commit preserves them even if no one is
        # around when the window opens.
        mkdir -p "$OUT/captured"
        cp "$OUT/$name.out" "$OUT/captured/$name.out" \
          || log "stage $name: mirror failed"
        log "stage $name: DONE"
      else
        rc=$?
        # A stage can fail because the relay wedged mid-run (re-probe
        # fails: fall back to the outer probe loop, retry the stage next
        # window — wedge kills do NOT count toward the attempt bound) or
        # on its own bug (relay still up: count the attempt and move on
        # so one bad stage can't block the queue behind it).
        if probe; then
          echo $((attempts + 1)) > "$OUT/$name.fail"
          log "stage $name: FAILED rc=$rc, relay up (attempt $((attempts + 1))/3) — continuing to next stage"
        else
          log "stage $name: FAILED rc=$rc, relay down — back to probing"
          break
        fi
      fi
    done < "$STAGES"
    if [ "$ran_any" = 0 ]; then
      log "no runnable stages (all done or perma-failed); idling"
      sleep $((SLEEP_S * 5)) 9>&-
      continue
    fi
  else
    log "probe failed (relay down)"
  fi
  sleep "$SLEEP_S" 9>&-
done
