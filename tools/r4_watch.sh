#!/bin/bash
# Round-4 TPU capture watcher.
#
# The box reaches its one TPU v5e chip through a relay that wedges for
# hours and comes back in windows sometimes only minutes long (see
# benchmarks/longrun_r3/README.md).  This watcher turns that into
# captured measurements: it probes the chip with a tiny matmul in a
# timeout-wrapped subprocess, and the moment a probe succeeds it runs the
# queued measurement stages in priority order, each under its own
# timeout, checkpointing completion per stage so an interrupted window
# resumes where it left off.
#
# Stages live in benchmarks/r4_capture/stages.txt, one per line:
#   name|timeout_seconds|command...
# The file is re-read every loop, so new stages can be appended while the
# watcher runs.  A stage is skipped once benchmarks/r4_capture/<name>.done
# exists; stdout/stderr land in <name>.out / <name>.err.
#
# Usage:  bash tools/r4_watch.sh   (run in background; tail watch.log)

set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/r4_capture
mkdir -p "$OUT"
STAGES="$OUT/stages.txt"

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }

probe() {
  timeout -k 10 90 python - >/dev/null 2>&1 <<'EOF'
import jax, jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
assert float((x @ x).sum()) > 0
EOF
}

log "watcher started (pid $$)"
while :; do
  if probe; then
    log "probe ok"
    ran_any=0
    while IFS='|' read -r name to cmd; do
      [ -z "${name:-}" ] && continue
      case "$name" in \#*) continue ;; esac
      [ -f "$OUT/$name.done" ] && continue
      ran_any=1
      log "stage $name: starting (timeout ${to}s): $cmd"
      if timeout -k 30 "$to" bash -c "$cmd" >"$OUT/$name.out" 2>"$OUT/$name.err"; then
        touch "$OUT/$name.done"
        log "stage $name: DONE"
      else
        rc=$?
        log "stage $name: FAILED rc=$rc — re-probing before next stage"
        break   # relay may have wedged mid-stage; fall back to probing
      fi
    done < "$STAGES"
    if [ "$ran_any" = 0 ]; then
      log "all stages done; idling (append to stages.txt to add work)"
      sleep 600
      continue
    fi
  else
    log "probe failed (relay down)"
  fi
  sleep 120
done
