#!/usr/bin/env python
"""obsctl — post-hoc forensic tooling over a run's observability artifacts.

Thin launcher around `tpu_dp.obs.obsctl` so the tool runs from a checkout
without installing the package:

    tools/obsctl.py timeline <run_dir>            # merged event stream
    tools/obsctl.py timeline <run_dir> --steps    # + per-step coverage
    tools/obsctl.py stragglers <run_dir>          # leave-one-out attribution
    tools/obsctl.py merge-trace <run_dir> -o t.json
    tools/obsctl.py diff <run_dir> --baseline BENCH_r08.json
    tools/obsctl.py diff <run_dir> --write-baseline base.json

Equivalent to ``python -m tpu_dp.obs``. Exit 0 clean / 1 regression
(diff) / 2 usage or artifact error. Needs no accelerator — postmortems
run anywhere the artifacts are readable.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dp.obs.obsctl import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
