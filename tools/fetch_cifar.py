#!/usr/bin/env python
"""Fetch CIFAR-10/100 into the pickle-batch layout `tpu_dp.data.cifar` reads.

The reference gets real data via `torchvision.datasets.CIFAR10(download=True)`
(`/root/reference/cifar_example.py:44-45`); this build environment has zero
network egress, so `tpu_dp.data.cifar.load_dataset` falls back to synthetic
data and every training artifact so far is synthetic (VERDICT r2 missing #1).
This tool is the egress-gated missing half: the moment the box can reach the
canonical host, one command materializes `<root>/cifar-10-batches-py/...`
(and/or the cifar-100 layout) — exactly the bytes torchvision would have
extracted — and the existing `--data.root` path trains on real CIFAR with no
other change:

    python tools/fetch_cifar.py --root ./data            # cifar10
    python tools/fetch_cifar.py --root ./data --dataset cifar100
    python tools/fetch_cifar.py --root ./data --verify   # check existing files

Without egress it fails fast (exit 2) with a clear diagnosis instead of
hanging — the gate probes the host with a short timeout before attempting
the ~170 MB transfer. Downloads are checksummed (the datasets' published
md5s) and extracted through a tar-member allowlist (no path traversal).
"""

from __future__ import annotations

import argparse
import hashlib
import socket
import sys
import tarfile
import tempfile
import urllib.error
import urllib.request
from pathlib import Path

HOST = "www.cs.toronto.edu"

# Canonical distribution: URL, published md5 of the .tar.gz, the directory
# the archive expands to, and the pickle-batch files load_dataset() needs
# (mirrors _SPECS in tpu_dp/data/cifar.py).
SPECS = {
    "cifar10": dict(
        url=f"https://{HOST}/~kriz/cifar-10-python.tar.gz",
        md5="c58f30108f718f92721af3b95e74349a",
        dirname="cifar-10-batches-py",
        files=[f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"],
    ),
    "cifar100": dict(
        url=f"https://{HOST}/~kriz/cifar-100-python.tar.gz",
        md5="eb9058c3a382ffc7106e4002c42a8d85",
        dirname="cifar-100-python",
        files=["train", "test"],
    ),
}


def egress_available(host: str = HOST, port: int = 443,
                     timeout_s: float = 5.0) -> bool:
    """True iff a TCP connection to the dataset host succeeds quickly.

    The whole probe — including DNS resolution, which
    `socket.create_connection`'s timeout does NOT bound and which can
    stall for minutes on a zero-egress box with black-holed resolvers —
    runs in a worker thread joined with a hard deadline.
    """
    import concurrent.futures

    def _probe() -> bool:
        with socket.create_connection((host, port), timeout=timeout_s):
            return True

    ex = concurrent.futures.ThreadPoolExecutor(1)
    try:
        return ex.submit(_probe).result(timeout=2 * timeout_s)
    except (OSError, concurrent.futures.TimeoutError):
        return False
    finally:
        ex.shutdown(wait=False)  # a DNS-stuck thread must not block exit


def download(url: str, dest: Path, expect_md5: str,
             timeout_s: float = 60.0) -> None:
    """Stream ``url`` to ``dest``, verifying the md5 of the received bytes."""
    digest = hashlib.md5()
    with urllib.request.urlopen(url, timeout=timeout_s) as r, \
            open(dest, "wb") as f:
        while True:
            chunk = r.read(1 << 20)
            if not chunk:
                break
            digest.update(chunk)
            f.write(chunk)
    got = digest.hexdigest()
    if got != expect_md5:
        dest.unlink(missing_ok=True)
        raise RuntimeError(
            f"md5 mismatch for {url}: got {got}, expected {expect_md5} "
            f"(truncated or tampered transfer)"
        )


def extract(tar_path: Path, root: Path, dirname: str,
            wanted: list[str]) -> list[Path]:
    """Extract only ``<dirname>/<wanted>`` members into ``root``.

    An explicit allowlist rather than `extractall`: the archive is fetched
    over the network, so no member may name a path outside
    ``root/<dirname>``.
    """
    out = []
    with tarfile.open(tar_path, "r:gz") as tf:
        names = {m.name: m for m in tf.getmembers()}
        for fname in wanted:
            member = names.get(f"{dirname}/{fname}")
            if member is None or not member.isfile():
                raise RuntimeError(
                    f"archive {tar_path.name} missing member "
                    f"{dirname}/{fname}"
                )
            dest = root / dirname / fname
            dest.parent.mkdir(parents=True, exist_ok=True)
            src = tf.extractfile(member)
            assert src is not None  # isfile() checked above
            with src, open(dest, "wb") as f:
                f.write(src.read())
            out.append(dest)
    return out


def verify_layout(root: Path, dataset: str) -> bool:
    """Load the on-disk layout through the production reader and report.

    The check is end-to-end: `load_dataset(allow_synthetic=False)` must
    return a non-synthetic dataset with the full example counts.
    """
    from tpu_dp.data.cifar import load_dataset

    ok = True
    for train, expect_n in ((True, 50_000), (False, 10_000)):
        split = "train" if train else "test"
        try:
            ds = load_dataset(dataset, root, train=train,
                              allow_synthetic=False)
        except Exception as e:  # noqa: BLE001 - report any failure class
            print(f"{dataset}/{split}: FAIL ({e})")
            ok = False
            continue
        good = not ds.synthetic and len(ds) == expect_n
        print(f"{dataset}/{split}: {'ok' if good else 'FAIL'} "
              f"({len(ds)} examples, {ds.num_classes} classes)")
        ok = ok and good
    return ok


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default="./data",
                    help="dataset root (the reference's ./data)")
    ap.add_argument("--dataset", default="cifar10", choices=sorted(SPECS))
    ap.add_argument("--verify", action="store_true",
                    help="only check an existing layout; no network")
    ap.add_argument("--probe-only", action="store_true",
                    help="exit 0 iff egress to the dataset host is open; "
                         "seconds, no chip, no jax — for the capture queue")
    ap.add_argument("--force", action="store_true",
                    help="re-download even if the layout verifies")
    args = ap.parse_args()
    root = Path(args.root)
    spec = SPECS[args.dataset]

    if args.verify:
        return 0 if verify_layout(root, args.dataset) else 1

    if args.probe_only:
        up = egress_available()
        print(f"egress to {HOST}:443: {'OPEN' if up else 'closed'}")
        return 0 if up else 2

    have = all((root / spec["dirname"] / f).exists() for f in spec["files"])
    if have and not args.force:
        print(f"{args.dataset} already present under {root / spec['dirname']}")
        return 0 if verify_layout(root, args.dataset) else 1

    if not egress_available():
        print(
            f"fetch_cifar: no egress to {HOST}:443 (probe timed out) — this "
            f"environment cannot download {args.dataset}. Run this command "
            f"from a host with network access, or copy an existing "
            f"{spec['dirname']}/ into {root}. Training falls back to "
            f"synthetic data until then.",
            file=sys.stderr,
        )
        return 2

    with tempfile.TemporaryDirectory() as td:
        tar_path = Path(td) / Path(spec["url"]).name
        print(f"downloading {spec['url']} ...")
        try:
            download(spec["url"], tar_path, spec["md5"])
        except (urllib.error.URLError, TimeoutError, OSError,
                RuntimeError) as e:
            # URLError: unreachable/HTTP failure; OSError: mid-stream reset;
            # RuntimeError: md5 mismatch. All are the same user story —
            # clean exit-2 diagnosis, per the module contract.
            print(f"fetch_cifar: download failed: {e}", file=sys.stderr)
            return 2
        print(f"extracting {len(spec['files'])} batch files into "
              f"{root / spec['dirname']} ...")
        extract(tar_path, root, spec["dirname"], spec["files"])

    return 0 if verify_layout(root, args.dataset) else 1


if __name__ == "__main__":
    sys.exit(main())
