#!/usr/bin/env python
"""Fleet straggler smoke: 3 CPU training processes, rank 2 delay-poisoned,
`obsctl fleet --replay` must name it — the `tools/run_tier1.sh --fleet` lane.

Spawns three real `Trainer` workers (gloo CPU collectives, obs=basic so
heartbeat step times are host-side windows — async dispatch keeps the
non-delayed ranks fast and the attribution clean), injects a composed
``delay:`` schedule that stalls rank 2 by 300ms at steps 14/16/18, and
verdicts the fleet layer end to end:

- ``obsctl fleet --replay`` over the faulty run exits 1 with BOTH rule
  grammars tripping (``fleet.skew_ratio>3`` and the self-baselining
  ``anomaly:step_time_ms 12``), and the worst-skew record names rank 2;
- the same command over a clean twin — same rules, same thresholds —
  exits 0;
- the published ``fleet.jsonl`` re-reads under the schema check.

Archives ``artifacts/fleet_report.json`` (the faulty run's fleet summary
+ the verdict). Exit 0 on a clean gate, 1 on any violated check.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # the driver imports the schema reader

RULES = ["--rule", "fleet.skew_ratio>3",
         "--rule", "anomaly:step_time_ms 12"]

_WORKER = r"""
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
fault = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from tpu_dp.config import Config
from tpu_dp.train.trainer import Trainer

cfg = Config()
cfg.data.dataset = "synthetic"
cfg.data.synthetic_train_size = 144
cfg.data.synthetic_test_size = 16
cfg.data.batch_size = 4
cfg.train.epochs = 2
cfg.train.log_every = 100
cfg.train.eval_at_end = False
cfg.train.steps_per_call = 1
cfg.train.ckpt_dir = ckpt
cfg.train.ckpt_async = False
cfg.train.obs = "basic"
if fault != "-":
    cfg.resilience.fault = fault
cfg.parallel.coordinator_address = f"127.0.0.1:{port}"
cfg.parallel.num_processes = 3
cfg.parallel.process_id = rank
Trainer(cfg).fit()
"""


def _run_world(tmp: Path, name: str, fault: str) -> tuple[Path, list[str]]:
    """One 3-process training run; returns (ckpt dir, failure list)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp / f"{name}_worker.py"
    script.write_text(_WORKER)
    ckpt = tmp / name
    env = dict(os.environ, PYTHONPATH=str(REPO))
    env.pop("TPU_DP_FAULT", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), port, str(ckpt), fault],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(3)
    ]
    logs, failures = [], []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=300)[0].decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        failures.append(f"{name}: training timed out")
        logs += [p.communicate()[0].decode() for p in procs[len(logs):]]
    for r, p in enumerate(procs):
        if p.returncode != 0:
            failures.append(f"{name} rank {r}: exit {p.returncode}")
    if failures:
        for r, log in enumerate(logs):
            print(f"--- {name} rank {r}\n{log[-2000:]}", file=sys.stderr)
    return ckpt, failures


def _fleet(run_dir: Path) -> tuple[int, dict]:
    cmd = [sys.executable, "-m", "tpu_dp.obs", "fleet", str(run_dir),
           "--replay", "--json", *RULES]
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          env=dict(os.environ, PYTHONPATH=str(REPO)))
    try:
        payload = json.loads(proc.stdout.strip().splitlines()[-1])
    except (IndexError, ValueError):
        payload = {}
    return proc.returncode, payload


def main() -> int:
    art = REPO / "artifacts"
    art.mkdir(parents=True, exist_ok=True)
    tmp = Path(tempfile.mkdtemp(prefix="tpu_dp_fleet_smoke."))
    t0 = time.time()
    fault = ";".join(f"delay:step={s},rank=2,ms=300" for s in (14, 16, 18))

    ck_faulty, failures = _run_world(tmp, "faulty", fault)
    ck_clean, f2 = _run_world(tmp, "clean", "-")
    failures += f2

    faulty_rc, faulty_out = (2, {})
    clean_rc, clean_out = (2, {})
    if not failures:
        faulty_rc, faulty_out = _fleet(ck_faulty)
        clean_rc, clean_out = _fleet(ck_clean)
        if faulty_rc != 1:
            failures.append(
                f"faulty run: obsctl fleet exit {faulty_rc} != 1")
        tripped = {ev.get("rule") for ev in faulty_out.get("alerts", [])}
        if tripped != set(RULES[1::2]):
            failures.append(f"faulty run: rules tripped {sorted(tripped)} "
                            f"!= both of {RULES[1::2]}")
        recs = []
        stream = ck_faulty / "obs" / "fleet.jsonl"
        if stream.exists():
            from tpu_dp.obs.fleet import read_fleet_records

            recs = read_fleet_records(stream)   # schema check is the point
        spikes = [r for r in recs if r.get("kind") == "fleet_step"
                  and r.get("skew_ratio", 0.0) >= 3.0]
        if not spikes:
            failures.append("faulty run: no >=3x skew record published")
        elif not all(r["slowest_rank"] == 2 for r in spikes):
            failures.append(
                f"mis-attributed: spike slowest_ranks "
                f"{sorted({r['slowest_rank'] for r in spikes})} != {{2}}")
        elif not {r["step"] for r in spikes} <= {14, 16, 18}:
            failures.append(f"spikes at {sorted(r['step'] for r in spikes)}"
                            f" not within the injected steps {{14, 16, 18}}")
        if clean_rc != 0:
            failures.append(f"clean twin: obsctl fleet exit {clean_rc} != 0"
                            f" (alerts: {clean_out.get('alerts')})")

    report = {
        "ok": not failures,
        "failures": failures,
        "wall_s": round(time.time() - t0, 1),
        "rules": RULES[1::2],
        "faulty": {"exit": faulty_rc, "report": faulty_out.get("report"),
                   "alerts": faulty_out.get("alerts")},
        "clean": {"exit": clean_rc, "report": clean_out.get("report")},
    }
    (art / "fleet_report.json").write_text(json.dumps(report, indent=2)
                                           + "\n")
    print(f"fleet smoke: {'OK' if not failures else 'FAIL'} "
          f"({report['wall_s']}s) — artifacts/fleet_report.json")
    if failures:
        for f in failures:
            print(f"  FAIL: {f}", file=sys.stderr)
        return 1
    shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
