#!/bin/bash
# Egress watcher (VERDICT r4 next-steps #5).
#
# The TPU capture queue (tools/r4_watch.sh) is gated on the *relay*; real
# CIFAR-10 is gated on *network egress* — an independent resource that
# could open at any time. This loop probes the dataset host every
# EGRESS_SLEEP_S (default 300 s) with no chip and no jax (the probe runs
# with PALLAS_AXON_POOL_IPS unset so the axon sitecustomize cannot hang
# the interpreter during a relay outage), logging every result so the
# round has positive evidence that egress never opened — or, the moment
# it does, fetches CIFAR-10 into ./data, verifies it through the
# production reader, queues the real-data training stage onto the TPU
# watcher's stage file (re-read each loop), and exits.
#
# Usage: nohup bash tools/egress_watch.sh >/dev/null 2>&1 &
# Test hooks: EGRESS_PROBE_CMD replaces the probe+fetch command,
# EGRESS_LOG overrides the log path, EGRESS_SLEEP_S the interval,
# EGRESS_STAGES the stage file appended to on success.

set -u
cd "$(dirname "$0")/.."
LOG="${EGRESS_LOG:-benchmarks/r4_capture/egress.log}"
STAGES="${EGRESS_STAGES:-benchmarks/r4_capture/stages.txt}"
SLEEP_S="${EGRESS_SLEEP_S:-300}"
mkdir -p "$(dirname "$LOG")"

log() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

exec 8>"$LOG.lock"
if ! flock -n 8; then
  log "another egress watcher holds $LOG.lock; exiting (pid $$)"
  exit 0
fi

probe() {
  if [ -n "${EGRESS_PROBE_CMD:-}" ]; then
    timeout -k 10 60 bash -c "$EGRESS_PROBE_CMD" >>"$LOG" 2>&1 8>&-
    return
  fi
  env -u PALLAS_AXON_POOL_IPS timeout -k 10 60 \
    python tools/fetch_cifar.py --probe-only >>"$LOG" 2>&1 8>&-
}

fetch() {
  if [ -n "${EGRESS_PROBE_CMD:-}" ]; then
    return 0  # test mode: probe cmd stands in for the whole pipeline
  fi
  env -u PALLAS_AXON_POOL_IPS timeout -k 30 900 \
    python tools/fetch_cifar.py --root ./data >>"$LOG" 2>&1 8>&-
}

log "egress watcher started (pid $$)"
while :; do
  if probe; then
    log "egress OPEN — fetching cifar10"
    if fetch; then
      log "fetch verified; queueing realdata stages"
      # Appended, not inserted: the fused/resnet50 evidence stages keep
      # priority; real-data training runs once the queue drains to it.
      # 30-epoch full recipe ≡ benchmarks/longrun_r3 but on real data —
      # the reference's 93% north star (cifar_example.py:111-112).
      cat >> "$STAGES" <<'EOF'
realdata_train|5400|python train.py --model.name=resnet18 --model.bf16=true --data.dataset=cifar10 --data.root=./data --data.batch_size=2048 --data.augment=true --data.prefetch=4 --optim.lr=0.4 --optim.schedule=cosine --optim.warmup_epochs=2 --optim.weight_decay=5e-4 --optim.decay_exclude_bias_and_norm=true --train.epochs=30 --train.log_every=8 --train.steps_per_call=24 --train.eval_every_epochs=5 --train.ckpt_dir=/tmp/realdata_r5 && mkdir -p benchmarks/realdata_r5 && cp /tmp/realdata_r5/metrics.jsonl benchmarks/realdata_r5/
EOF
      log "realdata_train queued; egress watcher done"
      exit 0
    else
      log "fetch FAILED (egress flapped?) — keep probing"
    fi
  else
    log "probe: closed"
  fi
  sleep "$SLEEP_S" 8>&-
done
