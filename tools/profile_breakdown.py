#!/usr/bin/env python
"""Trace one scanned bench window on the device and print an op breakdown.

Produces the numbers behind docs/DESIGN.md "Where the other half of peak
goes": captures a `jax.profiler` trace of a `make_multi_step` window
(identical config to bench.py's headline point), parses the xplane proto,
and aggregates device time by HLO category plus a per-op efficiency table
(achieved TFLOP/s and GB/s vs the chip's peaks).

    python tools/profile_breakdown.py                  # b2048, w30 (headline)
    python tools/profile_breakdown.py --per-chip-batch 1024 --window 30
    python tools/profile_breakdown.py --model resnet50 --per-chip-batch 1024
    python tools/profile_breakdown.py --fused-stages all   # fused Pallas path

Parsing notes (this environment): the Perfetto trace.json.gz export carries
host lanes only on this relay transport — the device lanes live in the
xplane.pb, read here via tensorflow's bundled xplane proto. The protobuf
runtime rejects that generated module under the C++ backend, so this tool
re-execs itself with PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python when
needed. Tracing inflates wall time (trace upload over the relay); the
*within-trace* device timestamps remain accurate, which is what's reported.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import tempfile
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

V5E_PEAK_TFLOPS = 197.0
V5E_PEAK_HBM_GBS = 819.0

# One source of truth for model -> num_classes: bench.py's MODEL_SPECS
# (BASELINE.json config 3 runs ResNet-50 on CIFAR-100).
from bench import MODEL_SPECS  # noqa: E402  (repo root on sys.path above)

MODEL_CLASSES = {name: spec[1] for name, spec in MODEL_SPECS.items()}


def capture(trace_dir: str, per_chip: int, window: int, model_name: str,
            fused_stages: str, fused_block_b: int, fused_bwd: bool,
            platform: str | None = None) -> None:
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.models import build_model, parse_fused_stages
    from tpu_dp.parallel import dist
    from tpu_dp.parallel.sharding import scan_batch_sharding, shard_batch
    from tpu_dp.train import SGD, cosine_lr, create_train_state, make_multi_step

    mesh = dist.data_mesh()
    gb = per_chip * int(mesh.devices.size)
    nc = MODEL_CLASSES[model_name]
    model = build_model(model_name, num_classes=nc, dtype=jnp.bfloat16,
                        fused_stages=parse_fused_stages(fused_stages),
                        fused_block_b=fused_block_b, fused_bwd=fused_bwd)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(model, jax.random.PRNGKey(0),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    pool_host = [make_synthetic(gb, nc, seed=i, name="bench") for i in range(4)]
    stacked = {"image": np.stack([d.images for d in pool_host]),
               "label": np.stack([d.labels for d in pool_host])}
    pool = shard_batch(stacked, mesh, spec=scan_batch_sharding(mesh))
    loop = make_multi_step(model, opt, mesh, cosine_lr(0.4, 2 * window, 2),
                           num_steps=window)
    state, m = loop(state, pool)  # compile + warmup
    float(m["loss"][-1])
    with jax.profiler.trace(trace_dir):
        state, m = loop(state, pool)
        float(m["loss"][-1])  # fence inside the trace


def report(trace_dir: str, top: int) -> None:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    if not paths:
        sys.exit(f"no xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(sorted(paths)[-1], "rb").read())
    devs = [p for p in xs.planes if p.name.startswith("/device:")
            and any(line.events for line in p.lines)]
    if not devs:
        sys.exit("no device plane with events (tracing unsupported here?)")
    dev = devs[0]
    md, sm = dev.event_metadata, dev.stat_metadata
    sname = {k: v.name for k, v in sm.items()}
    op_lines = [line for line in dev.lines if line.name == "XLA Ops"]
    if not op_lines:
        sys.exit(f"device plane {dev.name} has no 'XLA Ops' line "
                 f"(lines: {[line.name for line in dev.lines]})")
    ops = op_lines[0]

    by_cat = defaultdict(float)
    per_op = defaultdict(lambda: [0.0, 0, 0, 0])  # dur_s, flops, bytes, n
    window_s = 0.0
    for e in ops.events:
        m = md[e.metadata_id]
        if m.name.startswith("%while"):  # scan wrapper spans the whole window
            window_s += e.duration_ps / 1e12
            continue
        st = {sname[s.metadata_id]: s for s in m.stats}
        cat = st["hlo_category"].str_value if "hlo_category" in st else "?"
        by_cat[cat] += e.duration_ps / 1e12
        fl = (st["model_flops"].int64_value if "model_flops" in st
              else st["flops"].int64_value if "flops" in st else 0)
        by = st["bytes_accessed"].int64_value if "bytes_accessed" in st else 0
        rec = per_op[m.name.split(" = ")[0]]
        rec[0] += e.duration_ps / 1e12
        rec[1] += fl
        rec[2] += by
        rec[3] += 1

    total = sum(by_cat.values())
    if total <= 0:
        sys.exit("no non-wrapper op events in the trace — was a step "
                 "actually executed inside the profiled region?")
    print(f"\ndevice {dev.name}: window {window_s*1e3:.1f} ms, "
          f"op-busy {total*1e3:.1f} ms, idle {max(0, window_s-total)*1e3:.1f} ms")
    print("\n-- by HLO category --")
    for k, v in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"{v*1e3:9.1f} ms {100*v/total:6.1f}%  {k}")

    tot_f = sum(r[1] for r in per_op.values())
    print(f"\nmodel FLOPs in window: {tot_f/1e12:.2f} T "
          f"(avg {tot_f/total/1e12:.1f} TF/s, "
          f"{100*tot_f/total/(V5E_PEAK_TFLOPS*1e12):.0f}% of v5e bf16 peak)")
    print(f"\n-- top {top} ops by device time --")
    print(f"{'ms':>8} {'TF/s':>6} {'%peak':>6} {'GB/s':>7} {'n':>4}  op")
    for base, (d, f, b, n) in sorted(per_op.items(),
                                     key=lambda kv: -kv[1][0])[:top]:
        print(f"{d*1e3:8.1f} {f/d/1e12:6.1f} "
              f"{100*f/d/(V5E_PEAK_TFLOPS*1e12):6.1f} {b/d/1e9:7.0f} "
              f"{n:4d}  {base}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet18", choices=sorted(MODEL_CLASSES))
    ap.add_argument("--platform", default=None, choices=["cpu"],
                    help="force cpu (harness smoke test; the env's "
                         "sitecustomize pins the tpu backend, so the env "
                         "var alone is not enough)")
    ap.add_argument("--fused-stages", default="",
                    help="ResNet stages on the fused Pallas conv path "
                         "('', '0', 'all'; tpu_dp/ops/conv_block.py)")
    ap.add_argument("--fused-block-b", type=int, default=0)
    ap.add_argument("--fused-bwd", action="store_true")
    ap.add_argument("--per-chip-batch", type=int, default=2048)
    ap.add_argument("--window", type=int, default=30)
    ap.add_argument("--trace-dir", default=None,
                    help="reuse/keep a trace dir (default: temp, capture+report)")
    ap.add_argument("--report-only", action="store_true",
                    help="parse an existing --trace-dir without touching the device")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    # The TF-bundled xplane_pb2 needs the pure-python protobuf runtime.
    if os.environ.get("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION") != "python":
        os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
        os.execv(sys.executable, [sys.executable] + sys.argv)

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="tpu_dp_trace_")
    if not args.report_only:
        capture(trace_dir, args.per_chip_batch, args.window, args.model,
                args.fused_stages, args.fused_block_b, args.fused_bwd,
                platform=args.platform)
    report(trace_dir, args.top)
    print(f"\ntrace kept at {trace_dir}")


if __name__ == "__main__":
    main()
