#!/usr/bin/env python
"""Trace one scanned bench window on the device and print an op breakdown.

Produces the numbers behind docs/DESIGN.md "Where the other half of peak
goes": captures a `jax.profiler` trace of a `make_multi_step` window
(identical config to bench.py's headline point), parses the xplane proto
through `tpu_dp.obs.xplane` (the reusable library this tool is now a thin
CLI over — the in-run comm attribution layer `tpu_dp.obs.commprof` reads
traces through the same code path), and aggregates device time by HLO
category plus a per-op efficiency table (achieved TFLOP/s and GB/s vs the
chip's peaks, from the unified `tpu_dp.obs.chips` registry).

    python tools/profile_breakdown.py                  # b2048, w30 (headline)
    python tools/profile_breakdown.py --per-chip-batch 1024 --window 30
    python tools/profile_breakdown.py --model resnet50 --per-chip-batch 1024
    python tools/profile_breakdown.py --fused-stages all   # fused Pallas path

Parsing notes (this environment): the Perfetto trace.json.gz export carries
host lanes only on this relay transport — the device lanes live in the
xplane.pb. The protobuf runtime may reject TF's generated xplane module
under the C++ backend, so this tool re-execs itself with
PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python when needed (the documented
helper `tpu_dp.obs.xplane.reexec_with_python_protobuf`). Tracing inflates
wall time (trace upload over the relay); the *within-trace* device
timestamps remain accurate, which is what's reported. CPU-backend traces
have no device plane — inspect those with `python -m tpu_dp.obs.xplane`.
"""

from __future__ import annotations

import argparse
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

# One source of truth for model -> num_classes: bench.py's MODEL_SPECS
# (BASELINE.json config 3 runs ResNet-50 on CIFAR-100).
from bench import MODEL_SPECS  # noqa: E402  (repo root on sys.path above)
from tpu_dp.obs import chips  # noqa: E402  (unified chip-peak registry)

MODEL_CLASSES = {name: spec[1] for name, spec in MODEL_SPECS.items()}

#: The tool's historical target chip (the relay exposes one v5e); the
#: drift-prone local V5E_PEAK_* constants are gone — docs/DESIGN.md
#: numbers now cite the same registry MFU divides by.
_V5E = chips.chip_spec("v5e")


def capture(trace_dir: str, per_chip: int, window: int, model_name: str,
            fused_stages: str, fused_block_b: int, fused_bwd: bool,
            platform: str | None = None) -> None:
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.models import build_model, parse_fused_stages
    from tpu_dp.parallel import dist
    from tpu_dp.parallel.sharding import scan_batch_sharding, shard_batch
    from tpu_dp.train import SGD, cosine_lr, create_train_state, make_multi_step

    mesh = dist.data_mesh()
    gb = per_chip * int(mesh.devices.size)
    nc = MODEL_CLASSES[model_name]
    model = build_model(model_name, num_classes=nc, dtype=jnp.bfloat16,
                        fused_stages=parse_fused_stages(fused_stages),
                        fused_block_b=fused_block_b, fused_bwd=fused_bwd)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(model, jax.random.PRNGKey(0),
                               np.zeros((1, 32, 32, 3), np.float32), opt)
    pool_host = [make_synthetic(gb, nc, seed=i, name="bench") for i in range(4)]
    stacked = {"image": np.stack([d.images for d in pool_host]),
               "label": np.stack([d.labels for d in pool_host])}
    pool = shard_batch(stacked, mesh, spec=scan_batch_sharding(mesh))
    loop = make_multi_step(model, opt, mesh, cosine_lr(0.4, 2 * window, 2),
                           num_steps=window)
    state, m = loop(state, pool)  # compile + warmup
    float(m["loss"][-1])
    with jax.profiler.trace(trace_dir):
        state, m = loop(state, pool)
        float(m["loss"][-1])  # fence inside the trace


def report(trace_dir: str, top: int) -> None:
    """Parse + print the device-plane breakdown (output format unchanged
    from the pre-library versions; tests/test_profile_breakdown.py pins
    it). The heavy lifting — file discovery, proto parse, the %while
    wrapper/window split, per-op aggregation — is `tpu_dp.obs.xplane`'s."""
    from tpu_dp.obs import xplane

    path = xplane.find_xplane(trace_dir)
    if path is None:
        sys.exit(f"no xplane.pb under {trace_dir}")
    xs = xplane.load_xspace(path)
    devs = [p for p in xs.planes if p.name.startswith("/device:")
            and any(line.events for line in p.lines)]
    if not devs:
        sys.exit("no device plane with events (tracing unsupported here?)")
    dev = devs[0]
    if not any(line.name == "XLA Ops" for line in dev.lines):
        sys.exit(f"device plane {dev.name} has no 'XLA Ops' line "
                 f"(lines: {[line.name for line in dev.lines]})")
    s = xplane.device_plane_summary(dev)

    by_cat = s["by_category"]
    window_s = s["window_s"]
    total = sum(by_cat.values())
    if total <= 0:
        sys.exit("no non-wrapper op events in the trace — was a step "
                 "actually executed inside the profiled region?")
    print(f"\ndevice {dev.name}: window {window_s*1e3:.1f} ms, "
          f"op-busy {total*1e3:.1f} ms, idle {max(0, window_s-total)*1e3:.1f} ms")
    print("\n-- by HLO category --")
    for k, v in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"{v*1e3:9.1f} ms {100*v/total:6.1f}%  {k}")

    tot_f = sum(r["flops"] for r in s["ops"])
    print(f"\nmodel FLOPs in window: {tot_f/1e12:.2f} T "
          f"(avg {tot_f/total/1e12:.1f} TF/s, "
          f"{100*tot_f/total/_V5E.peak_flops:.0f}% of v5e bf16 peak)")
    print(f"\n-- top {top} ops by device time --")
    print(f"{'ms':>8} {'TF/s':>6} {'%peak':>6} {'GB/s':>7} {'n':>4}  op")
    for r in s["ops"][:top]:
        d, f, b, n = r["dur_s"], r["flops"], r["bytes"], r["count"]
        print(f"{d*1e3:8.1f} {f/d/1e12:6.1f} "
              f"{100*f/d/_V5E.peak_flops:6.1f} {b/d/1e9:7.0f} "
              f"{n:4d}  {r['name']}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet18", choices=sorted(MODEL_CLASSES))
    ap.add_argument("--platform", default=None, choices=["cpu"],
                    help="force cpu (harness smoke test; the env's "
                         "sitecustomize pins the tpu backend, so the env "
                         "var alone is not enough)")
    ap.add_argument("--fused-stages", default="",
                    help="ResNet stages on the fused Pallas conv path "
                         "('', '0', 'all'; tpu_dp/ops/conv_block.py)")
    ap.add_argument("--fused-block-b", type=int, default=0)
    ap.add_argument("--fused-bwd", action="store_true")
    ap.add_argument("--per-chip-batch", type=int, default=2048)
    ap.add_argument("--window", type=int, default=30)
    ap.add_argument("--trace-dir", default=None,
                    help="reuse/keep a trace dir (default: temp, capture+report)")
    ap.add_argument("--report-only", action="store_true",
                    help="parse an existing --trace-dir without touching the device")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    # The TF-bundled xplane_pb2 may need the pure-python protobuf runtime;
    # the re-exec hack lives in the library now (one documented helper).
    from tpu_dp.obs.xplane import reexec_with_python_protobuf

    reexec_with_python_protobuf()

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="tpu_dp_trace_")
    if not args.report_only:
        capture(trace_dir, args.per_chip_batch, args.window, args.model,
                args.fused_stages, args.fused_block_b, args.fused_bwd,
                platform=args.platform)
    report(trace_dir, args.top)
    print(f"\ntrace kept at {trace_dir}")


if __name__ == "__main__":
    main()
