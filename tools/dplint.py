#!/usr/bin/env python
"""dplint — static SPMD-correctness analyzer for tpu_dp.

Thin launcher around `tpu_dp.analysis` so the tool runs from a checkout
without installing the package:

    tools/dplint.py                    # all three levels over tpu_dp/
    tools/dplint.py --no-jaxpr --no-hlo path   # AST rules only (pre-commit)
    tools/dplint.py --baseline ci.json # suppress pre-existing findings
    tools/dplint.py --list-rules
    tools/dplint.py host               # Level 4: host-protocol rules
                                       # (DP401-DP405) over the tree
    tools/dplint.py host --list-rules  # the Level-4 rule table
    tools/dplint.py conc               # Level 5: concurrency rules
                                       # (DP501-DP505) over the tree
    tools/dplint.py --changed          # lint only files differing from
                                       # the merge-base (pre-commit loop)

`--changed` composes with every mode (`tools/dplint.py conc --changed`,
`tools/dplint.py --changed --no-jaxpr --no-hlo`): it resolves the git
repository of the *current directory*, diffs the working tree against
the merge-base with the default branch (plus untracked files), and
substitutes the changed ``.py`` files as the paths to lint. With nothing
changed it prints a note and exits 0, so an empty pre-commit run passes.

Equivalent to `python -m tpu_dp.analysis`. Exit 0 clean / 1 findings /
2 internal or usage error (partial findings still rendered on stdout).
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dp.analysis.cli import main  # noqa: E402


def _changed_files() -> list[str]:
    """Working-tree ``.py`` files differing from the merge-base with the
    default branch, plus untracked ones — the pre-commit question "what
    did I touch", asked of the repository the user is standing in."""

    def _git(*args: str) -> str:
        return subprocess.run(
            ["git", *args], check=True, capture_output=True, text=True,
        ).stdout

    root = _git("rev-parse", "--show-toplevel").strip()
    base = "HEAD"
    for ref in ("origin/main", "main", "origin/master", "master"):
        try:
            base = _git("merge-base", "HEAD", ref).strip()
            break
        except subprocess.CalledProcessError:
            continue
    # On the default branch itself the merge-base IS HEAD, so the diff
    # degrades to staged + unstaged edits — still the pre-commit answer.
    names = _git("diff", "--name-only", "--diff-filter=d", base)
    names += _git("ls-files", "--others", "--exclude-standard")
    out: list[str] = []
    for name in names.splitlines():
        if not name.endswith(".py"):
            continue
        path = os.path.join(root, name)
        if os.path.exists(path) and path not in out:
            out.append(path)
    return out


def _main() -> int:
    argv = sys.argv[1:]
    if "--changed" not in argv:
        return main(argv)
    argv = [a for a in argv if a != "--changed"]
    try:
        changed = _changed_files()
    except (subprocess.CalledProcessError, OSError) as e:
        print(f"dplint: --changed needs a git checkout: {e}",
              file=sys.stderr)
        return 2
    if not changed:
        print("dplint: no python files differ from the merge-base")
        return 0
    return main(argv + changed)


if __name__ == "__main__":
    sys.exit(_main())
