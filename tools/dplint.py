#!/usr/bin/env python
"""dplint — static SPMD-correctness analyzer for tpu_dp.

Thin launcher around `tpu_dp.analysis` so the tool runs from a checkout
without installing the package:

    tools/dplint.py                    # all three levels over tpu_dp/
    tools/dplint.py --no-jaxpr --no-hlo path   # AST rules only (pre-commit)
    tools/dplint.py --baseline ci.json # suppress pre-existing findings
    tools/dplint.py --list-rules
    tools/dplint.py host               # Level 4: host-protocol rules
                                       # (DP401-DP405) over the tree
    tools/dplint.py host --list-rules  # the Level-4 rule table

Equivalent to `python -m tpu_dp.analysis`. Exit 0 clean / 1 findings /
2 internal or usage error (partial findings still rendered on stdout).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dp.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
