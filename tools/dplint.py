#!/usr/bin/env python
"""dplint — static SPMD-correctness analyzer for tpu_dp.

Thin launcher around `tpu_dp.analysis` so the tool runs from a checkout
without installing the package:

    tools/dplint.py                  # analyze the tpu_dp package (both levels)
    tools/dplint.py --no-jaxpr path  # AST rules only
    tools/dplint.py --list-rules

Equivalent to `python -m tpu_dp.analysis`. Exit 0 clean / 1 findings.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dp.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
