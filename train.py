#!/usr/bin/env python
"""Train entry point — one script, one code path, any mesh size.

Replaces the reference's forked pair (`/root/reference/cifar_example.py` run
directly vs `cifar_example_ddp.py` under `torchrun --nproc_per_node=N`): the
same command runs single-chip or across a full slice; parallelism comes from
the visible devices (and, multi-host, from `--parallel.*` / the standard JAX
coordination env), not from a launcher fork.

Usage:
    python train.py                                  # reference parity: Net, batch 4, 2 epochs
    python train.py --preset=resnet18_cifar10
    python train.py --preset=bf16_cosine_gb4096 --train.epochs=5
    python train.py --data.dataset=synthetic --train.log_every=50
    python train.py --config=checkpoints/step_0000000042/meta.json \
        --train.ckpt_dir=./repro   # reproduce into a fresh checkpoint dir
    python train.py --resume=auto  # continue from the newest checkpoint or
                                   # snapshot if one exists, else start fresh

Any config field is overridable as `--section.field=value` (see
`tpu_dp/config.py`). Preemption (SIGTERM/SIGINT) snapshots and exits with
code 143; an auto-restarting supervisor that relaunches with
`--resume=auto` loses no steps (docs/RESILIENCE.md).
"""

import json
import sys

from tpu_dp.config import parse_cli
from tpu_dp.resilience import DivergedError, PreemptedError
from tpu_dp.train.trainer import Trainer, run_elastic
from tpu_dp.utils import print0


def main(argv=None) -> int:
    cfg = parse_cli(sys.argv[1:] if argv is None else argv)
    try:
        if cfg.resilience.elastic:
            # The relaunch-aware driver: identical to Trainer(cfg).fit()
            # except that a fired `relaunch:` fault rejoins the run
            # in-process instead of exiting 143 (docs/RESILIENCE.md
            # "Fault-injection spec"); it also lets a relaunched process
            # JOIN a live run via resilience.elastic_join.
            trainer, result = run_elastic(cfg)
        else:
            trainer = Trainer(cfg)
            result = trainer.fit()
    except PreemptedError as e:
        # Clean preemption: the final snapshot is committed; exit with the
        # conventional terminated-by-SIGTERM status so supervisors restart
        # (with --resume=auto) instead of flagging a failure.
        print0(f"preempted: {e}")
        return PreemptedError.exit_code
    except DivergedError as e:
        # Guardrail halt: training is mathematically compromised (NaN
        # storm, unrecoverable divergence, SDC). Exit 65 (EX_DATAERR) —
        # deliberately NOT 143 — so a supervisor does not auto-restart
        # into the same divergence (docs/RESILIENCE.md "Guardrails").
        print0(f"diverged: {e}")
        return DivergedError.exit_code
    summary = {
        "model": cfg.model.name,
        "dataset": trainer.train_ds.name,
        "synthetic": trainer.train_ds.synthetic,
        "devices": trainer.num_devices,
        "images_per_sec": round(result["images_per_sec"], 1),
        "wall_time_s": round(result["wall_time_s"], 1),
        "final_train_loss": round(result["history"][-1]["loss"], 4)
        if result["history"] else None,
        "eval": result.get("eval"),
    }
    if trainer.guard_enabled:
        # Guardrail rollup: quarantines/rollbacks/audits must be visible
        # in the one-line summary, not only in quarantine.jsonl.
        from tpu_dp.obs.counters import counters as obs_counters

        summary["guard"] = {
            "quarantined": int(obs_counters.get("guard.quarantined")),
            "spikes": int(obs_counters.get("guard.spike")),
            "rollbacks": int(obs_counters.get("guard.rollbacks")),
            "sdc_audits": int(obs_counters.get("guard.sdc_audits")),
            "sdc_mismatches": int(obs_counters.get("guard.sdc_mismatches")),
            "quarantine_log": str(trainer.quarantine_path),
        }
    obs = trainer.obs_summary()
    if obs is not None:
        # Telemetry rollup (train.obs=basic|full): span percentiles +
        # counters in the same summary line the run already emits.
        summary["obs"] = obs
        if "efficiency" in obs:
            # MFU/goodput get headline placement: hardware utilization is
            # the first-class fleet health signal (arXiv:2204.06514), not
            # a nested detail — and this is the block `obsctl diff`
            # cross-checks against BENCH baselines.
            summary["efficiency"] = obs["efficiency"]
    if trainer.elastic is not None:
        # Elastic rollup: a shrink must be visible in the one-line summary,
        # not only in the membership ledger (docs/RESILIENCE.md).
        from tpu_dp.obs.counters import counters as obs_counters

        rec = trainer.elastic.record
        summary["elastic"] = {
            "membership_epoch": rec.epoch,
            "world": rec.world,
            "members": list(rec.members),
            "regroups": int(obs_counters.get("elastic.regroups")),
            "lost_ranks": int(obs_counters.get("elastic.lost_ranks")),
            "joined_ranks": int(obs_counters.get("elastic.joined_ranks")),
            "regroup_s": round(obs_counters.get("elastic.regroup_s"), 3),
        }
    print0(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
