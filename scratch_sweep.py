"""TPU perf experiments (scratch, not part of the framework).

Run when the TPU relay is live: sweeps per-chip batch and loss impl through
the scanned-window measurement bench.py uses. Usage:
    python scratch_sweep.py 1024 2048 4096     # batch sizes to try
    PALLAS=1 python scratch_sweep.py 2048      # fused pallas xent loss
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dp.data.cifar import make_synthetic
from tpu_dp.models import ResNet18
from tpu_dp.parallel import dist
from tpu_dp.parallel.sharding import scan_batch_sharding, shard_batch
from tpu_dp.train import SGD, cosine_lr, create_train_state, make_multi_step

mesh = dist.data_mesh()
n = int(mesh.devices.size)
STEPS = 30
use_pallas = os.environ.get("PALLAS", "0") == "1"

for batch in [int(a) for a in sys.argv[1:]] or (2048,):
    model = ResNet18(num_classes=10, dtype=jnp.bfloat16)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    loop = make_multi_step(
        model, opt, mesh, cosine_lr(0.4, 2 * STEPS, 2), num_steps=STEPS,
        use_pallas_xent=use_pallas,
    )
    pool_ds = [make_synthetic(batch * n, 10, seed=i, name="bench") for i in range(4)]
    pool = shard_batch(
        {"image": np.stack([d.images for d in pool_ds]),
         "label": np.stack([d.labels for d in pool_ds])},
        mesh, spec=scan_batch_sharding(mesh),
    )
    state, m = loop(state, pool)
    float(m["loss"][-1])  # fence (axon relay: block_until_ready lies)
    t0 = time.perf_counter()
    state, m = loop(state, pool)
    float(m["loss"][-1])
    dt = time.perf_counter() - t0
    ips = STEPS * batch * n / dt / n
    print(f"batch/chip={batch} pallas={use_pallas}: {ips:.0f} img/s/chip "
          f"({dt / STEPS * 1e3:.1f} ms/step)", flush=True)
